"""Fused LSTM layer as Pallas TPU kernels.

The reference's fused-RNN performance story is the cuDNN v5 kernel
(cudnn_rnn-inl.h); the XLA translation (ops/rnn.py) batches the input
projection into one big MXU gemm and scans the recurrence — but under
a `lax.scan` the recurrent weight matrix streams from HBM on EVERY
step, so the serial part of the layer is HBM-bound: T steps re-read
4H*H weights each (e.g. S=128, H=512 -> ~1 GB of weight traffic for
8 MB of weights).

These kernels run the whole time loop as ONE grid with the recurrent
weights and the (h, c) state resident in VMEM: per step only the
precomputed gate inputs gx[t] stream in and h[t] streams out — weight
traffic drops from O(T * H^2) to O(H^2).  The forward kernel also
writes the post-activation gates and cell states, which the backward
kernel (same structure, reverse-streamed via its index maps) consumes
to produce d_gx, d_Wh, d_bh, d_h0, d_c0 without any recomputation.

Sequential-grid semantics (TPU Pallas executes the grid in order,
scratch persists across steps) are what make the carried state legal —
the same property the flash-attention kernels rely on for their
running-softmax accumulators.

``interpret=True`` (tests, CPU) runs identical kernel code through the
Pallas interpreter.  Eligibility for the jit path is checked by
:func:`fused_lstm_eligible`; `ops/rnn.py` falls back to the scan
otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_util import idx32

__all__ = ["fused_lstm", "fused_lstm_eligible"]


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _sigmoid(x):
    return jax.nn.sigmoid(x)


# -- forward ------------------------------------------------------------------

def _fwd_kernel(gx_ref, h0_ref, c0_ref, wh_ref, bh_ref,
                *refs, T, H, save):
    if save:
        ys_ref, hT_ref, cT_ref, acts_ref, cells_ref, h_sc, c_sc = refs
    else:
        ys_ref, hT_ref, cT_ref, h_sc, c_sc = refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_sc[:] = h0_ref[:].astype(jnp.float32)
        c_sc[:] = c0_ref[:].astype(jnp.float32)

    # recurrent matmul in the ACTIVATION dtype (bf16 MXU fast path; f32
    # runs the ~4x slower pass) — keyed off gx like the flash kernels,
    # so f32 master weights with bf16 activations still engage it.  The
    # carried state itself stays f32 in scratch for stability across T
    # steps; only matmul operands are cast, accumulation is f32 via
    # preferred_element_type.
    dt_lo = gx_ref.dtype
    gates = (gx_ref[0].astype(jnp.float32)
             + jax.lax.dot_general(h_sc[:].astype(dt_lo),
                                   wh_ref[:].astype(dt_lo),
                                   (((1,), (1,)), ((), ())),
                                   preferred_element_type=jnp.float32)
             + bh_ref[0].astype(jnp.float32))
    i = _sigmoid(gates[:, 0 * H:1 * H])
    f = _sigmoid(gates[:, 1 * H:2 * H])
    g = jnp.tanh(gates[:, 2 * H:3 * H])
    o = _sigmoid(gates[:, 3 * H:4 * H])
    c = f * c_sc[:] + i * g
    h = o * jnp.tanh(c)
    if save:
        acts_ref[0] = jnp.concatenate([i, f, g, o], axis=-1)
        cells_ref[0] = c
    ys_ref[0] = h.astype(ys_ref.dtype)
    h_sc[:] = h
    c_sc[:] = c

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h.astype(hT_ref.dtype)
        cT_ref[:] = c.astype(cT_ref.dtype)


def _fwd(gx, h0, c0, wh, bh, interpret, save):
    """``save=False`` (inference / undifferentiated primal) skips the
    residual outputs — a pallas_call cannot have unused outputs DCE'd,
    and the backward residuals are 5x the useful HBM write traffic."""
    T, N, G = gx.shape
    H = G // 4
    kernel = functools.partial(_fwd_kernel, T=T, H=H, save=save)
    full = idx32(lambda t: (0, 0))
    step3 = idx32(lambda t: (t, 0, 0))
    out_specs = [
        pl.BlockSpec((1, N, H), step3),
        pl.BlockSpec((N, H), full),
        pl.BlockSpec((N, H), full),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, N, H), gx.dtype),       # ys
        jax.ShapeDtypeStruct((N, H), gx.dtype),          # hT
        jax.ShapeDtypeStruct((N, H), gx.dtype),          # cT
    ]
    if save:
        out_specs += [pl.BlockSpec((1, N, G), step3),
                      pl.BlockSpec((1, N, H), step3)]
        out_shape += [jax.ShapeDtypeStruct((T, N, G), jnp.float32),
                      jax.ShapeDtypeStruct((T, N, H), jnp.float32)]
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, G), step3),
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((G, H), full),
            pl.BlockSpec((1, G), full),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((N, H), jnp.float32),
        ],
        interpret=interpret,
    )(gx, h0, c0, wh, bh)


# -- backward -----------------------------------------------------------------

def _bwd_kernel(acts_ref, cells_ref, cprev_ref, hprev_ref, h0_ref, c0_ref,
                wh_ref, dys_ref, dhT_ref, dcT_ref,
                dgx_ref, dwh_ref, dbh_ref, dh0_ref, dc0_ref,
                dh_sc, dc_sc, dwh_sc, dbh_sc, *, T, H):
    rt = pl.program_id(0)          # reverse step; actual time t = T-1-rt
    t = T - 1 - rt

    @pl.when(rt == 0)
    def _():
        dh_sc[:] = dhT_ref[:].astype(jnp.float32)
        dc_sc[:] = dcT_ref[:].astype(jnp.float32)
        dwh_sc[:] = jnp.zeros_like(dwh_sc)
        dbh_sc[:] = jnp.zeros_like(dbh_sc)

    acts = acts_ref[0]
    i = acts[:, 0 * H:1 * H]
    f = acts[:, 1 * H:2 * H]
    g = acts[:, 2 * H:3 * H]
    o = acts[:, 3 * H:4 * H]
    c = cells_ref[0]
    is_first = t == 0
    c_prev = jnp.where(is_first, c0_ref[:].astype(jnp.float32),
                       cprev_ref[0])
    h_prev = jnp.where(is_first, h0_ref[:].astype(jnp.float32),
                       hprev_ref[0].astype(jnp.float32))

    dh = dh_sc[:] + dys_ref[0].astype(jnp.float32)
    tc = jnp.tanh(c)
    do = dh * tc
    dc = dc_sc[:] + dh * o * (1.0 - tc * tc)
    di = dc * g
    df = dc * c_prev
    dg = dc * i
    dgates = jnp.concatenate(
        [di * i * (1.0 - i), df * f * (1.0 - f),
         dg * (1.0 - g * g), do * o * (1.0 - o)], axis=-1)   # (N, 4H)

    dgx_ref[0] = dgates.astype(dgx_ref.dtype)
    # matmul operands in the activation dtype (MXU fast path, f32 acc)
    dt_lo = dgx_ref.dtype
    dg_lo = dgates.astype(dt_lo)
    # dWh += dgates^T @ h_prev : contract over batch
    dwh_sc[:] += jax.lax.dot_general(dg_lo, h_prev.astype(dt_lo),
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
    dbh_sc[0, :] += jnp.sum(dgates, axis=0)
    dh_sc[:] = jnp.dot(dg_lo, wh_ref[:].astype(dt_lo),
                       preferred_element_type=jnp.float32)
    dc_sc[:] = dc * f

    @pl.when(rt == T - 1)
    def _():
        dh0_ref[:] = dh_sc[:].astype(dh0_ref.dtype)
        dc0_ref[:] = dc_sc[:].astype(dc0_ref.dtype)
        dwh_ref[:] = dwh_sc[:].astype(dwh_ref.dtype)
        dbh_ref[0] = dbh_sc[0].astype(dbh_ref.dtype)


def _bwd_call(acts, cells, ys, h0, c0, wh, dys, dhT, dcT, gx_dtype,
              interpret):
    T, N, G = acts.shape
    H = G // 4
    kernel = functools.partial(_bwd_kernel, T=T, H=H)
    full = idx32(lambda rt: (0, 0))
    rev = idx32(lambda rt: (T - 1 - rt, 0, 0))
    # previous-step streams: block t-1 (clamped at 0; the t==0 value is
    # replaced by h0/c0 inside the kernel)
    rev_m1 = idx32(lambda rt: (jnp.maximum(T - 2 - rt, 0), 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, G), rev),        # acts[t]
            pl.BlockSpec((1, N, H), rev),        # cells[t]
            pl.BlockSpec((1, N, H), rev_m1),     # cells[t-1]
            pl.BlockSpec((1, N, H), rev_m1),     # ys[t-1] == h_{t-1}
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((G, H), full),
            pl.BlockSpec((1, N, H), rev),        # dys[t]
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((N, H), full),
        ],
        out_specs=[
            pl.BlockSpec((1, N, G), rev),        # dgx[t]
            pl.BlockSpec((G, H), full),
            pl.BlockSpec((1, G), full),
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((N, H), full),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, G), gx_dtype),
            jax.ShapeDtypeStruct((G, H), jnp.float32),
            jax.ShapeDtypeStruct((1, G), jnp.float32),
            jax.ShapeDtypeStruct((N, H), jnp.float32),
            jax.ShapeDtypeStruct((N, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((G, H), jnp.float32),
            pltpu.VMEM((1, G), jnp.float32),
        ],
        interpret=interpret,
    )(acts, cells, cells, ys, h0, c0, wh, dys, dhT, dcT)


# -- public entry with custom VJP ---------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused(gx, h0, c0, wh, bh, interpret):
    # undifferentiated path (inference): no residual outputs
    ys, hT, cT = _fwd(gx, h0, c0, wh, bh, interpret, save=False)
    return ys, hT, cT


def _fused_fwd(gx, h0, c0, wh, bh, interpret):
    ys, hT, cT, acts, cells = _fwd(gx, h0, c0, wh, bh, interpret,
                                   save=True)
    return (ys, hT, cT), (acts, cells, ys, h0, c0, wh, bh)


def _fused_bwd(interpret, res, grads):
    acts, cells, ys, h0, c0, wh, bh = res
    dys, dhT, dcT = grads
    dgx, dwh, dbh, dh0, dc0 = _bwd_call(
        acts, cells, ys, h0, c0, wh,
        dys.astype(ys.dtype), dhT.astype(ys.dtype), dcT.astype(ys.dtype),
        ys.dtype, interpret)
    # dbh keeps the (1, G) shape and dtype of the reshaped primal; the
    # outer reshape's own vjp restores (G,)
    return (dgx, dh0.astype(h0.dtype), dc0.astype(c0.dtype),
            dwh.astype(wh.dtype), dbh.astype(bh.dtype))


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_lstm_eligible(T, N, H, force=None):
    """Whether the fused kernel should carry this layer on the current
    backend.  Lane/sublane alignment keeps Mosaic happy; the VMEM
    budget bounds the weight + dWh accumulator residency.

    ``force`` / ``MXNET_TPU_FUSED_RNN=1`` override the backend and
    sequence-length gates (interpret-mode tests, benchmarking) but the
    Mosaic alignment and VMEM constraints still apply on a real TPU —
    forcing a shape the compiler cannot tile must fall back, not crash.
    """
    import os

    env = os.environ.get("MXNET_TPU_FUSED_RNN", "")
    if env == "0":
        return False
    forced = bool(force) or env == "1"
    on_tpu = _on_tpu()
    if on_tpu:
        if H % 128 or N % 8:
            return False
        # VMEM residency: wh + dwh-accumulator f32 (weight term) plus
        # the batch-proportional working set — h/c scratch, the per-step
        # (N,4H)/(N,H) in/out blocks and their pipelining double
        # buffers (~24 (N,H)-equivalents is a conservative count).
        # Oversize shapes must fall back to the scan, not crash Mosaic.
        weight_bytes = 2 * 4 * H * H * 4
        batch_bytes = 24 * N * H * 4
        if weight_bytes + batch_bytes > 12 * 1024 * 1024:
            return False
    if forced:
        return True
    if not on_tpu:
        return False
    return T >= 8  # tiny sequences gain nothing over the scan


def fused_lstm(gx, h0, c0, wh, bh, interpret=None):
    """One LSTM layer over precomputed gate inputs.

    Args:
      gx: (T, N, 4H) input projection incl. input bias (x @ Wi^T + bi).
      h0, c0: (N, H) initial states.
      wh: (4H, H) recurrent weights; bh: (4H,) recurrent bias.
      interpret: run through the Pallas interpreter (default: off-TPU).

    Returns ``(ys, hT, cT)`` with ys (T, N, H).  Differentiable w.r.t.
    all five array arguments (custom VJP, reverse-streamed kernel).
    Gate order i, f, g, o matches ops/rnn.py's scan cell.
    """
    if interpret is None:
        interpret = not _on_tpu()
    T, N, G = gx.shape
    H = G // 4
    if wh.shape != (G, H):
        raise ValueError(f"wh must be {(G, H)}, got {wh.shape}")
    return _fused(gx, h0.astype(jnp.float32), c0.astype(jnp.float32),
                  wh, bh.reshape(1, G), bool(interpret))
