"""Operator definition contract and registry.

Rebuild of the reference's two operator registration systems:

- ``OperatorProperty`` full operators (include/mxnet/operator.h:165+,
  registered via ``MXNET_REGISTER_OP_PROPERTY``, discovered by name in a
  dmlc registry — src/operator/operator.cc:11-22), and
- the lighter "simple op" framework for the elementwise / reduce / matrix
  zoo (``MXNET_REGISTER_SIMPLE_OP``, include/mxnet/operator_util.h:243-486).

TPU-native design: an op does **not** carry device kernels.  It carries
metadata (arguments, outputs, aux states, shape/dtype inference, a typed
``Params`` struct) plus a single JAX-traceable ``forward`` — XLA owns
kernel codegen for every device.  Ops with non-vjp backward semantics
(loss layers, BlockGrad) declare an explicit ``backward``; the graph
compiler wraps those in ``jax.custom_vjp`` so whole-graph autodiff
(the MakeBackwardPass equivalent) composes through them.

The registry is the runtime-discoverable op surface: ``mxnet_tpu.ndarray``
and ``mxnet_tpu.symbol`` generate their functions from it at import time,
mirroring the reference frontends' use of
``MXSymbolListAtomicSymbolCreators`` (python/mxnet/symbol.py:999-1120).
"""

from __future__ import annotations

import numpy as np

from ..base import np_dtype
from ..param import Params
from ..registry import Registry

__all__ = ["OpDef", "OP_REGISTRY", "register_op", "register_simple_op", "SimpleOpDef"]

OP_REGISTRY = Registry("operator")


class OpDef:
    """Metadata + JAX lowering for one operator.

    Subclasses override class attributes / methods as needed.  All shape
    values are tuples of ints, with ``None`` marking "unknown" entries fed
    to bidirectional inference (symbolic.h InferShape contract).
    """

    name: str = None
    param_cls: type = None
    need_rng: bool = False  # op consumes a PRNG key (Dropout, samplers)
    is_loss: bool = False  # backward ignores head gradient (SoftmaxOutput &co)
    # name of the param the frontends fill with the positional-input
    # count when not given (reference key_var_num_args, an OPT-IN per-op
    # property: Concat/ElementWiseSum/Crop/UpSampling — the last ignores
    # it for the signature in bilinear mode, like the reference)
    key_var_num_args: str = None

    # -- signature ---------------------------------------------------------
    def list_arguments(self, params) -> list:
        return ["data"]

    def list_outputs(self, params) -> list:
        return ["output"]

    def list_auxiliary_states(self, params) -> list:
        return []

    def num_inputs(self, params) -> int:
        return len(self.list_arguments(params))

    def num_outputs(self, params) -> int:
        return len(self.list_outputs(params))

    # -- inference ---------------------------------------------------------
    def infer_shape(self, params, in_shapes):
        """Return (in_shapes, out_shapes, aux_shapes), completing Nones.

        Default: single output with the shape of input 0 (identity-like).
        """
        if in_shapes[0] is None:
            raise ValueError(f"{self.name}: cannot infer shape, input 0 unknown")
        return list(in_shapes), [tuple(in_shapes[0])], []

    def infer_dtype(self, params, in_dtypes):
        """Return (in_dtypes, out_dtypes, aux_dtypes)."""
        dt = next((d for d in in_dtypes if d is not None), np.dtype(np.float32))
        return [d if d is not None else dt for d in in_dtypes], [dt] * self.num_outputs(params), [
            dt
        ] * len(self.list_auxiliary_states(params))

    # -- lowering ----------------------------------------------------------
    def forward(self, params, inputs, aux, train, key):
        """JAX-traceable computation.

        Parameters
        ----------
        params : Params or None
        inputs : list of jnp arrays (traced)
        aux : list of jnp arrays (auxiliary states, e.g. BN moving stats)
        train : bool (static)
        key : jax PRNG key or None (present iff ``need_rng``)

        Returns
        -------
        (outputs, new_aux) : both lists of jnp arrays.  ``new_aux`` must
        have the same structure as ``aux`` (unchanged entries passed
        through); it is committed by the executor after a training step.
        """
        raise NotImplementedError

    # Ops with explicit backward semantics (loss layers) override this.
    # Returning None means "differentiate forward with jax.vjp".
    def backward(self, params, out_grads, inputs, outputs):
        """Explicit gradient: return grads w.r.t. every input.

        ``out_grads`` are head gradients (ignored by loss ops, which is
        exactly the reference's SoftmaxOutput contract,
        src/operator/softmax_output-inl.h).
        """
        return None

    has_backward = False  # set True when ``backward`` is overridden

    def make_params(self, kwargs) -> Params:
        if self.param_cls is None:
            if kwargs:
                raise ValueError(f"{self.name} takes no keyword params, got {sorted(kwargs)}")
            return None
        return self.param_cls(**kwargs)

    def __repr__(self):
        return f"<Op {self.name}>"


def register_op(name, aliases=()):
    """Class decorator: instantiate and register an OpDef subclass."""

    def _reg(cls):
        inst = cls()
        inst.name = name
        if "backward" in cls.__dict__:
            inst.has_backward = True
        OP_REGISTRY.register(name, inst, aliases=aliases)
        return cls

    return _reg


class SimpleOpDef(OpDef):
    """One-liner op: n inputs -> 1 output via a jnp function.

    The rebuild of MXNET_REGISTER_SIMPLE_OP: register the kernel once,
    get both the NDArray function and the Symbol op, on every device.
    """

    def __init__(self, name, fn, nin=1, shape_rule="same", dtype_rule="same",
                 param_cls=None, arg_names=None, is_loss=False, backward_fn=None,
                 need_rng=False):
        self.name = name
        self.fn = fn
        self.nin = nin
        self.shape_rule = shape_rule
        self.dtype_rule = dtype_rule
        self.param_cls = param_cls
        self.arg_names = arg_names or (["data"] if nin == 1 else
                                       ["lhs", "rhs", "mhs"][:nin])
        self.is_loss = is_loss
        self.backward_fn = backward_fn
        self.has_backward = backward_fn is not None
        self.need_rng = need_rng

    def list_arguments(self, params):
        return list(self.arg_names)

    def infer_shape(self, params, in_shapes):
        known = [s for s in in_shapes if s is not None]
        if not known:
            raise ValueError(f"{self.name}: no input shape known")
        rule = self.shape_rule
        if callable(rule):
            out = rule(params, in_shapes)
            if isinstance(out, tuple) and len(out) == 2:
                in_shapes, out_shape = out
            else:
                out_shape = out
            return list(in_shapes), [tuple(out_shape)], []
        if rule == "same":
            ref = known[0]
            return [ref if s is None else s for s in in_shapes], [tuple(ref)], []
        if rule == "broadcast":
            ref = tuple(np.broadcast_shapes(*known))
            return list(in_shapes), [ref], []
        raise ValueError(f"bad shape rule {rule!r}")

    def infer_dtype(self, params, in_dtypes):
        if callable(self.dtype_rule):
            return self.dtype_rule(params, in_dtypes)
        dt = next((d for d in in_dtypes if d is not None), np.dtype(np.float32))
        return [d if d is not None else dt for d in in_dtypes], [dt], []

    def forward(self, params, inputs, aux, train, key):
        if self.need_rng:
            out = self.fn(params, *inputs, key=key) if params is not None or self.param_cls \
                else self.fn(*inputs, key=key)
        elif self.param_cls is not None:
            out = self.fn(params, *inputs)
        else:
            out = self.fn(*inputs)
        return [out], []

    def backward(self, params, out_grads, inputs, outputs):
        if self.backward_fn is None:
            return None
        return self.backward_fn(params, out_grads, inputs, outputs)


def register_simple_op(name, fn, nin=1, aliases=(), **kw):
    op = SimpleOpDef(name, fn, nin=nin, **kw)
    OP_REGISTRY.register(name, op, aliases=aliases)
    return op


def as_np_dtype(d):
    return None if d is None else np_dtype(d)
