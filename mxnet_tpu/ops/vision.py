"""Vision operators: ROIPooling, SpatialTransformer, Correlation.

Rebuild of src/operator/{roi_pooling,spatial_transformer,correlation}-inl.h
(+ their .cu kernels).  All three are expressed as vectorized gather/mask
computations with static shapes so XLA can fuse and tile them — no scalar
loops over pixels (the reference's CUDA thread-per-output pattern maps to
whole-array ops here).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..param import Params, field, tuple_of
from .op import OpDef, register_op


# -- ROIPooling --------------------------------------------------------------
class ROIPoolingParam(Params):
    pooled_size = field(tuple_of(int), required=True)
    spatial_scale = field(float, required=True)


@register_op("ROIPooling")
class ROIPoolingOp(OpDef):
    """Max-pool features inside each ROI into a fixed grid
    (roi_pooling-inl.h).  rois: (R, 5) rows [batch_idx, x1, y1, x2, y2]."""

    param_cls = ROIPoolingParam

    def list_arguments(self, params):
        return ["data", "rois"]

    def infer_shape(self, params, in_shapes):
        data, rois = in_shapes
        if data is None or rois is None:
            raise ValueError("ROIPooling: shapes unknown")
        ph, pw = params.pooled_size
        return list(in_shapes), [(rois[0], data[1], ph, pw)], []

    def forward(self, params, inputs, aux, train, key):
        data, rois = inputs
        N, C, H, W = data.shape
        ph, pw = params.pooled_size
        scale = params.spatial_scale

        def one_roi(roi):
            bidx = roi[0].astype(jnp.int32)
            x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
            y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
            x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
            y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            ys = jnp.arange(H)
            xs = jnp.arange(W)
            # bin index per pixel (or ph/pw = "outside")
            by = jnp.where((ys >= y1) & (ys <= y2),
                           jnp.clip(((ys - y1) * ph) // rh, 0, ph - 1), ph)
            bx = jnp.where((xs >= x1) & (xs <= x2),
                           jnp.clip(((xs - x1) * pw) // rw, 0, pw - 1), pw)
            flat_bin = by[:, None] * (pw + 1) + bx[None, :]  # (H, W)
            feat = data[bidx]  # (C, H, W)
            out = jnp.full((C, (ph + 1) * (pw + 1)), -jnp.inf, data.dtype)
            out = out.at[:, flat_bin.reshape(-1)].max(
                feat.reshape(C, -1), mode="drop")
            out = out.reshape(C, ph + 1, pw + 1)[:, :ph, :pw]
            return jnp.where(jnp.isfinite(out), out, 0.0)

        return [jax.vmap(one_roi)(rois)], []


# -- SpatialTransformer ------------------------------------------------------
class SpatialTransformerParam(Params):
    target_shape = field(tuple_of(int), required=True)
    transform_type = field(str, default="affine", enum=("affine",))
    sampler_type = field(str, default="bilinear", enum=("bilinear",))


@register_op("SpatialTransformer")
class SpatialTransformerOp(OpDef):
    """Affine grid generator + bilinear sampler
    (spatial_transformer-inl.h / cudnn_spatial_transformer-inl.h).
    loc input: (N, 6) affine parameters."""

    param_cls = SpatialTransformerParam

    def list_arguments(self, params):
        return ["data", "loc"]

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        th, tw = params.target_shape
        return [tuple(data), (data[0], 6)], [(data[0], data[1], th, tw)], []

    def forward(self, params, inputs, aux, train, key):
        data, loc = inputs
        N, C, H, W = data.shape
        th, tw = params.target_shape
        theta = loc.reshape(N, 2, 3).astype(jnp.float32)
        # normalized target grid in [-1, 1]
        ys = jnp.linspace(-1.0, 1.0, th)
        xs = jnp.linspace(-1.0, 1.0, tw)
        gx, gy = jnp.meshgrid(xs, ys)  # (th, tw)
        ones = jnp.ones_like(gx)
        grid = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, th*tw)
        src = jnp.einsum("nij,jk->nik", theta, grid)  # (N, 2, th*tw)
        sx = (src[:, 0] + 1.0) * (W - 1) / 2.0
        sy = (src[:, 1] + 1.0) * (H - 1) / 2.0

        x0 = jnp.floor(sx)
        y0 = jnp.floor(sy)
        wx = sx - x0
        wy = sy - y0

        def sample(img, yi, xi):
            """img (C,H,W); gather with zero padding outside."""
            valid = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            vals = img[:, yc, xc]  # (C, P)
            return vals * valid.astype(img.dtype)

        def one(img, x0, y0, wx, wy):
            v00 = sample(img, y0, x0)
            v01 = sample(img, y0, x0 + 1)
            v10 = sample(img, y0 + 1, x0)
            v11 = sample(img, y0 + 1, x0 + 1)
            out = (v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
                   + v10 * (1 - wx) * wy + v11 * wx * wy)
            return out.reshape(C, th, tw)

        out = jax.vmap(one)(data, x0, y0, wx.astype(data.dtype),
                            wy.astype(data.dtype))
        return [out.astype(data.dtype)], []


# -- Correlation -------------------------------------------------------------
class CorrelationParam(Params):
    kernel_size = field(int, default=1)
    max_displacement = field(int, default=1)
    stride1 = field(int, default=1)
    stride2 = field(int, default=1)
    pad_size = field(int, default=0)
    is_multiply = field(bool, default=True)


@register_op("Correlation")
class CorrelationOp(OpDef):
    """Optical-flow cost volume between two feature maps
    (correlation-inl.h): for each displacement (du, dv) on the stride2
    grid within max_displacement, mean over channels+kernel window of
    f1(x) * f2(x + d)  (or |f1 - f2| when is_multiply=False)."""

    param_cls = CorrelationParam

    def list_arguments(self, params):
        return ["data1", "data2"]

    def _geometry(self, params, H, W):
        pad = params.pad_size
        bd = params.max_displacement
        k = params.kernel_size
        kr = k // 2
        ph, pw = H + 2 * pad, W + 2 * pad
        d = 2 * bd // params.stride2 + 1
        oh = int(np.ceil((ph - (k - 1) - 2 * bd) / params.stride1))
        ow = int(np.ceil((pw - (k - 1) - 2 * bd) / params.stride1))
        return d, oh, ow, pad, bd, kr

    def infer_shape(self, params, in_shapes):
        n, c, H, W = in_shapes[0]
        d, oh, ow, *_ = self._geometry(params, H, W)
        return [tuple(in_shapes[0])] * 2, [(n, d * d, oh, ow)], []

    def forward(self, params, inputs, aux, train, key):
        f1, f2 = inputs
        N, C, H, W = f1.shape
        d, oh, ow, pad, bd, kr = self._geometry(params, H, W)
        k, s1, s2 = params.kernel_size, params.stride1, params.stride2
        p1 = jnp.pad(f1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        p2 = jnp.pad(f2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
        # output grid top-left corners in padded coords
        base = bd + kr
        outs = []
        for dy in range(-bd, bd + 1, s2):
            for dx in range(-bd, bd + 1, s2):
                # window sums over kernel_size at each output position
                acc = 0.0
                for ky in range(-kr, k - kr):
                    for kx in range(-kr, k - kr):
                        a = lax.dynamic_slice(
                            p1, (0, 0, base + ky, base + kx),
                            (N, C, (oh - 1) * s1 + 1, (ow - 1) * s1 + 1)
                        )[:, :, ::s1, ::s1]
                        b = lax.dynamic_slice(
                            p2, (0, 0, base + dy + ky, base + dx + kx),
                            (N, C, (oh - 1) * s1 + 1, (ow - 1) * s1 + 1)
                        )[:, :, ::s1, ::s1]
                        acc = acc + (a * b if params.is_multiply
                                     else jnp.abs(a - b))
                outs.append(jnp.sum(acc, axis=1) / (k * k * C))
        out = jnp.stack(outs, axis=1)  # (N, d*d, oh, ow)
        return [out.astype(f1.dtype)], []
