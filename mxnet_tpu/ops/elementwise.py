"""Elementwise unary / binary / scalar / broadcast operators.

Rebuild of the reference's simple-op zoo:
src/operator/{elemwise_unary_op,elementwise_binary_op,
elementwise_binary_scalar_op,elementwise_binary_broadcast_op}.cc plus the
scalar functor zoo in src/operator/mshadow_op.h.  Each registration yields
both an imperative NDArray function and a Symbol op, as in the reference's
MXNET_REGISTER_SIMPLE_OP pattern.  Kernels are jnp expressions — XLA fuses
them into surrounding computations (the mshadow expression-template role).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..param import Params, field
from .op import OpDef, register_op, register_simple_op


class ScalarParam(Params):
    """Scalar operand for *_scalar ops (operator_util.h scalar ops)."""

    scalar = field(float, required=True, doc="scalar operand")


def _unary(name, fn, aliases=()):
    register_simple_op(name, fn, nin=1, aliases=aliases)


def _binary(name, fn, aliases=()):
    register_simple_op(name, fn, nin=2, shape_rule="broadcast", aliases=aliases)


def _scalar(name, fn):
    register_simple_op(name, fn, nin=1, param_cls=ScalarParam)


# -- unary (mshadow_op.h functors) ------------------------------------------
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", lambda x: 1.0 / jnp.sqrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("cos", jnp.cos)
_unary("sin", jnp.sin)
_unary("tanh", jnp.tanh)
_unary("sigmoid", lambda x: 1.0 / (1.0 + jnp.exp(-x)))
_unary("relu", lambda x: jnp.maximum(x, 0))
_unary("negative", lambda x: -x, aliases=("_mul_scalar_neg",))
_unary("_copy", lambda x: x)
_unary("gamma", lambda x: jnp.exp(__import__("jax").scipy.special.gammaln(x)))
_unary("gammaln", lambda x: __import__("jax").scipy.special.gammaln(x))
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)

# -- binary (same-shape in the reference; we additionally broadcast) ---------
_binary("_plus", jnp.add, aliases=("elemwise_add", "_add"))
_binary("_minus", jnp.subtract, aliases=("elemwise_sub", "_sub"))
_binary("_mul", jnp.multiply, aliases=("elemwise_mul",))
_binary("_div", jnp.divide, aliases=("elemwise_div",))
_binary("_power", jnp.power, aliases=("pow",))
_binary("_maximum", jnp.maximum)
_binary("_minimum", jnp.minimum)
_binary("_hypot", jnp.hypot)

# comparison family (returns same dtype as inputs, like the reference)
_binary("_equal", lambda a, b: (a == b).astype(a.dtype))
_binary("_not_equal", lambda a, b: (a != b).astype(a.dtype))
_binary("_greater", lambda a, b: (a > b).astype(a.dtype))
_binary("_greater_equal", lambda a, b: (a >= b).astype(a.dtype))
_binary("_lesser", lambda a, b: (a < b).astype(a.dtype))
_binary("_lesser_equal", lambda a, b: (a <= b).astype(a.dtype))

# -- broadcast_* explicit family (elementwise_binary_broadcast_op.cc) --------
_binary("broadcast_plus", jnp.add, aliases=("broadcast_add",))
_binary("broadcast_minus", jnp.subtract, aliases=("broadcast_sub",))
_binary("broadcast_mul", jnp.multiply)
_binary("broadcast_div", jnp.divide)
_binary("broadcast_power", jnp.power)
_binary("broadcast_maximum", jnp.maximum)
_binary("broadcast_minimum", jnp.minimum)

# -- scalar variants ---------------------------------------------------------
_scalar("_plus_scalar", lambda p, x: x + p.scalar)
_scalar("_minus_scalar", lambda p, x: x - p.scalar)
_scalar("_rminus_scalar", lambda p, x: p.scalar - x)
_scalar("_mul_scalar", lambda p, x: x * p.scalar)
_scalar("_div_scalar", lambda p, x: x / p.scalar)
_scalar("_rdiv_scalar", lambda p, x: p.scalar / x)
_scalar("_power_scalar", lambda p, x: x**p.scalar)
_scalar("_rpower_scalar", lambda p, x: p.scalar**x)
_scalar("_maximum_scalar", lambda p, x: jnp.maximum(x, p.scalar))
_scalar("_minimum_scalar", lambda p, x: jnp.minimum(x, p.scalar))
_scalar("_equal_scalar", lambda p, x: (x == p.scalar).astype(x.dtype))
_scalar("_not_equal_scalar", lambda p, x: (x != p.scalar).astype(x.dtype))
_scalar("_greater_scalar", lambda p, x: (x > p.scalar).astype(x.dtype))
_scalar("_greater_equal_scalar", lambda p, x: (x >= p.scalar).astype(x.dtype))
_scalar("_lesser_scalar", lambda p, x: (x < p.scalar).astype(x.dtype))
_scalar("_lesser_equal_scalar", lambda p, x: (x <= p.scalar).astype(x.dtype))


class ElementWiseSumParam(Params):
    num_args = field(int, required=True, lower=1, doc="number of summands")


@register_op("ElementWiseSum", aliases=("add_n", "element_wise_sum"))
class ElementWiseSumOp(OpDef):
    """Variadic sum (src/operator/elementwise_sum-inl.h; also the NDArray
    function ElementwiseSum, src/ndarray/ndarray.cc:292+)."""

    key_var_num_args = "num_args"

    param_cls = ElementWiseSumParam

    def list_arguments(self, params):
        return [f"arg{i}" for i in range(params.num_args)]

    def infer_shape(self, params, in_shapes):
        known = next((s for s in in_shapes if s is not None), None)
        if known is None:
            raise ValueError("ElementWiseSum: no input shape known")
        for s in in_shapes:
            if s is not None and tuple(s) != tuple(known):
                raise ValueError(
                    f"ElementWiseSum: all inputs must share one shape, "
                    f"got {tuple(s)} vs {tuple(known)}")
        return [known if s is None else s for s in in_shapes], [tuple(known)], []

    def forward(self, params, inputs, aux, train, key):
        out = inputs[0]
        for x in inputs[1:]:
            out = out + x
        return [out], []


def _element_mask(lhs, rhs):
    return lhs * rhs.reshape((rhs.shape[0],) + (1,) * (lhs.ndim - 1)).astype(lhs.dtype)


def _element_mask_shape(params, in_shapes):
    lhs, rhs = in_shapes
    if lhs is None:
        raise ValueError("element_mask: lhs shape unknown")
    if len(lhs) < 2 or (rhs is not None and (len(rhs) != 1 or rhs[0] != lhs[0])):
        raise ValueError("element_mask: lhs must be >=2D, rhs 1D with matching dim0")
    return [lhs, (lhs[0],)], tuple(lhs)


def _element_mask_backward(params, out_grads, inputs, outputs):
    # Mask is non-differentiable w.r.t. rhs (broadcast_mask_op-inl.h:59-82
    # writes only lhs_grad).
    og = out_grads[0]
    return [_element_mask(og, inputs[1]), jnp.zeros_like(inputs[1])]


register_simple_op("element_mask", _element_mask, nin=2,
                   shape_rule=_element_mask_shape,
                   backward_fn=_element_mask_backward)


class SmoothL1Param(Params):
    sigma = field(float, default=1.0, doc="transition point scale")


def _smooth_l1(p, x):
    s2 = p.sigma * p.sigma
    absx = jnp.abs(x)
    return jnp.where(absx < 1.0 / s2, 0.5 * s2 * x * x, absx - 0.5 / s2)


register_simple_op("smooth_l1", _smooth_l1, nin=1, param_cls=SmoothL1Param)


class ClipParam(Params):
    a_min = field(float, required=True)
    a_max = field(float, required=True)


register_simple_op("clip", lambda p, x: jnp.clip(x, p.a_min, p.a_max), nin=1,
                   param_cls=ClipParam)
