"""Sequence operators with per-example lengths.

Rebuild of src/operator/sequence_{last,mask,reverse}-inl.h (+
sequence_op_common.h).  Layout convention matches the reference:
time-major (T, N, ...) with an optional (N,) length vector.
Implemented with vectorized masks/gathers — no scalar loops, so XLA
keeps everything on-device with static shapes.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..param import Params, field
from .op import OpDef, register_op


class SequenceParam(Params):
    use_sequence_length = field(bool, default=False)


class SequenceMaskParam(SequenceParam):
    value = field(float, default=0.0)


def _seq_args(params):
    return ["data", "sequence_length"] if params.use_sequence_length else ["data"]


@register_op("SequenceLast")
class SequenceLastOp(OpDef):
    param_cls = SequenceParam

    def list_arguments(self, params):
        return _seq_args(params)

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        completed = [tuple(d)] + ([(d[1],)] if params.use_sequence_length else [])
        return completed, [tuple(d[1:])], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        if params.use_sequence_length:
            idx = (inputs[1].astype(jnp.int32) - 1).clip(0, x.shape[0] - 1)
            out = jnp.take_along_axis(
                x, idx.reshape((1, -1) + (1,) * (x.ndim - 2)), axis=0
            )[0]
        else:
            out = x[-1]
        return [out], []


@register_op("SequenceMask")
class SequenceMaskOp(OpDef):
    param_cls = SequenceMaskParam

    def list_arguments(self, params):
        return _seq_args(params)

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        completed = [tuple(d)] + ([(d[1],)] if params.use_sequence_length else [])
        return completed, [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        if not params.use_sequence_length:
            return [x], []
        steps = jnp.arange(x.shape[0]).reshape((-1, 1))
        mask = steps < inputs[1].astype(jnp.int32).reshape((1, -1))
        mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
        return [jnp.where(mask, x, params.value).astype(x.dtype)], []


@register_op("SequenceReverse")
class SequenceReverseOp(OpDef):
    param_cls = SequenceParam

    def list_arguments(self, params):
        return _seq_args(params)

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        completed = [tuple(d)] + ([(d[1],)] if params.use_sequence_length else [])
        return completed, [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        if not params.use_sequence_length:
            return [jnp.flip(x, axis=0)], []
        T = x.shape[0]
        lengths = inputs[1].astype(jnp.int32).reshape((1, -1))
        steps = jnp.arange(T).reshape((-1, 1))
        # index of source row: reverse within [0, len), identity beyond
        src = jnp.where(steps < lengths, lengths - 1 - steps, steps)
        out = jnp.take_along_axis(x, src.reshape(src.shape + (1,) * (x.ndim - 2)),
                                  axis=0)
        return [out], []
