"""Output / loss-layer operators with explicit backward semantics.

Rebuild of src/operator/{softmax_output,regression_output,make_loss,
block_grad,svm_output}-inl.h.  These ops define ``backward`` explicitly:
their gradient is the gradient of an *implicit* loss and ignores the head
gradient — e.g. SoftmaxOutput's backward is ``(softmax(x) - onehot(label))
* grad_scale`` regardless of out_grad.  The graph compiler wraps them in
``jax.custom_vjp`` so whole-graph reverse-mode flows through correctly.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..param import Params, field
from .op import OpDef, register_op, register_simple_op


class SoftmaxOutputParam(Params):
    grad_scale = field(float, default=1.0)
    ignore_label = field(float, default=-1.0)
    multi_output = field(bool, default=False)
    use_ignore = field(bool, default=False)
    preserve_shape = field(bool, default=False)
    normalization = field(str, default="null", enum=("null", "batch", "valid"))
    out_grad = field(bool, default=False,
                     doc="scale the gradient by the incoming output "
                         "gradient (softmax_output-inl.h:132)")


@register_op("SoftmaxOutput", aliases=("Softmax",))
class SoftmaxOutputOp(OpDef):
    """Softmax forward + cross-entropy gradient backward
    (softmax_output-inl.h:386: grad_scale, ignore_label, multi_output)."""

    param_cls = SoftmaxOutputParam
    is_loss = True

    def list_arguments(self, params):
        return ["data", "label"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("SoftmaxOutput: data shape unknown")
        given = in_shapes[1] if len(in_shapes) > 1 else None
        # label.shape == data.shape: use probability as label
        # (softmax_output-inl.h InferShape first branch)
        if given is not None and tuple(given) == tuple(d):
            return [tuple(d), tuple(d)], [tuple(d)], []
        if params.multi_output:
            # data (n, c, d1...), label (n, d1...) (or flattened variants)
            label = (d[0],) + tuple(d[2:])
            n_rest = int(np.prod(d)) // (d[0] * d[1]) if len(d) > 1 else 1
            variants = {label, (d[0], n_rest), tuple(d[:1]) + (1,) + tuple(d[2:])}
            if given is not None and tuple(given) in variants:
                label = tuple(given)
        else:
            label = (d[0],)
        return [tuple(d), label], [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        axis = 1 if params.multi_output else -1
        if not params.multi_output and x.ndim > 2 and not params.preserve_shape:
            out = jax.nn.softmax(x.reshape(x.shape[0], -1)).reshape(x.shape)
        else:
            out = jax.nn.softmax(x, axis=axis)
        return [out], []

    def backward(self, params, out_grads, inputs, outputs):
        prob = outputs[0]
        label = inputs[1]
        axis = 1 if params.multi_output else -1
        if label.shape == prob.shape:
            # probability labels (soft targets)
            grad = prob - label.astype(prob.dtype)
            if params.out_grad and out_grads and out_grads[0] is not None:
                grad = grad * out_grads[0].astype(grad.dtype)
            grad = grad * params.grad_scale
            return [grad, jnp.zeros_like(label)]
        nclass = prob.shape[axis]
        lab = label.astype(jnp.int32)
        if params.multi_output:
            # canonicalise every accepted label variant to (n, d1, ...):
            # (n,1,d1,...) and the flattened (n, prod(d1...)) both reshape
            # to the spatial layout of prob minus its class axis
            spatial = prob.shape[:1] + prob.shape[2:]
            if lab.shape != spatial:
                lab = lab.reshape(spatial)
        onehot = jax.nn.one_hot(lab, nclass, dtype=prob.dtype, axis=axis)
        grad = prob - onehot
        if params.out_grad and out_grads and out_grads[0] is not None:
            grad = grad * out_grads[0].astype(grad.dtype)
        mask = None
        if params.use_ignore:
            mask = (lab != int(params.ignore_label))
            grad = grad * jnp.expand_dims(mask, axis).astype(grad.dtype)
        if params.multi_output:
            # reference softmax_output-inl.h multi-output scaling: the
            # spatial extent always divides (grad_scale/s3[2] in null
            # mode, grad_scale/(s3[2]*n) in batch mode), and valid-count
            # normalization applies whether or not use_ignore is set
            # (all positions count as valid without ignore)
            spatial = max(int(np.prod(prob.shape[2:])), 1)
            if params.normalization == "valid":
                valid = (jnp.maximum(jnp.sum(mask), 1).astype(grad.dtype)
                         if mask is not None else float(lab.size))
                grad = grad / valid
            elif params.normalization == "batch":
                grad = grad / (spatial * prob.shape[0])
            else:
                grad = grad / spatial
        else:
            if params.normalization == "valid":
                # valid_cnt == label.Size() when nothing is ignored
                valid = (jnp.maximum(jnp.sum(mask), 1).astype(grad.dtype)
                         if mask is not None else float(lab.size))
                grad = grad / valid
            elif params.normalization == "batch":
                grad = grad / prob.shape[0]
        grad = grad * params.grad_scale
        return [grad, jnp.zeros_like(label)]


class RegressionParam(Params):
    grad_scale = field(float, default=1.0)


def _reg_label_shape(self, params, in_shapes):
    """Label-shape rule of regression_output-inl.h:105-130: default the
    label to (n,) for (n, 1) outputs / data shape otherwise, and accept
    any provided label with matching batch dim and total size."""
    d = in_shapes[0]
    if d is None:
        raise ValueError("regression output: data shape unknown")
    d = tuple(d)
    lbl = in_shapes[1]
    if lbl is None:
        lbl = (d[0],) if len(d) == 2 and d[1] == 1 else d
    else:
        lbl = tuple(lbl)
        if (lbl[0] != d[0]
                or int(np.prod(lbl)) != int(np.prod(d))):
            raise ValueError(
                f"regression output: shape inconsistent, provided label "
                f"{lbl}, inferred {d}")
    return [d, lbl], [d], []


@register_op("LinearRegressionOutput")
class LinearRegressionOutputOp(OpDef):
    """Identity forward, (pred - label) backward (regression_output-inl.h)."""

    param_cls = RegressionParam
    is_loss = True

    def list_arguments(self, params):
        return ["data", "label"]

    infer_shape = _reg_label_shape

    def forward(self, params, inputs, aux, train, key):
        return [inputs[0]], []

    def backward(self, params, out_grads, inputs, outputs):
        scale = params.grad_scale / outputs[0].shape[0]
        g = (outputs[0] - inputs[1].reshape(outputs[0].shape)) * scale
        return [g, jnp.zeros_like(inputs[1])]


@register_op("MAERegressionOutput")
class MAERegressionOutputOp(LinearRegressionOutputOp):
    def backward(self, params, out_grads, inputs, outputs):
        scale = params.grad_scale / outputs[0].shape[0]
        g = jnp.sign(outputs[0] - inputs[1].reshape(outputs[0].shape)) * scale
        return [g, jnp.zeros_like(inputs[1])]


@register_op("LogisticRegressionOutput")
class LogisticRegressionOutputOp(OpDef):
    """Sigmoid forward, (sigmoid(x) - label) backward."""

    param_cls = RegressionParam
    is_loss = True

    def list_arguments(self, params):
        return ["data", "label"]

    infer_shape = _reg_label_shape

    def forward(self, params, inputs, aux, train, key):
        return [jax.nn.sigmoid(inputs[0])], []

    def backward(self, params, out_grads, inputs, outputs):
        scale = params.grad_scale / outputs[0].shape[0]
        g = (outputs[0] - inputs[1].reshape(outputs[0].shape)) * scale
        return [g, jnp.zeros_like(inputs[1])]


class MakeLossParam(Params):
    grad_scale = field(float, default=1.0)
    valid_thresh = field(float, default=0.0)
    normalization = field(str, default="null", enum=("null", "batch", "valid"))


@register_op("MakeLoss")
class MakeLossOp(OpDef):
    """Turn any symbol into a loss: forward = identity, backward = grad_scale
    (make_loss-inl.h)."""

    param_cls = MakeLossParam
    is_loss = True

    def forward(self, params, inputs, aux, train, key):
        return [inputs[0]], []

    def backward(self, params, out_grads, inputs, outputs):
        x = inputs[0]
        if params.normalization == "valid":
            # reference (make_loss-inl.h:84-93): grad_scale / #valid at
            # EVERY position — the count normalizes, it does not mask
            valid = jnp.maximum(
                jnp.sum((x > params.valid_thresh).astype(x.dtype)), 1.0)
            g = jnp.full_like(x, params.grad_scale) / valid
        elif params.normalization == "batch":
            g = jnp.full_like(x, params.grad_scale / x.shape[0])
        else:
            g = jnp.full_like(x, params.grad_scale)
        return [g]


@register_op("BlockGrad", aliases=("stop_gradient",))
class BlockGradOp(OpDef):
    """Identity forward, zero backward (block_grad-inl.h) — stop_gradient."""

    is_loss = True

    def forward(self, params, inputs, aux, train, key):
        return [jax.lax.stop_gradient(inputs[0])], []

    def backward(self, params, out_grads, inputs, outputs):
        return [jnp.zeros_like(inputs[0])]


class SVMOutputParam(Params):
    margin = field(float, default=1.0)
    regularization_coefficient = field(float, default=1.0)
    use_linear = field(bool, default=False)


@register_op("SVMOutput")
class SVMOutputOp(OpDef):
    """Hinge-loss output layer (svm_output-inl.h)."""

    param_cls = SVMOutputParam
    is_loss = True

    def list_arguments(self, params):
        return ["data", "label"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        return [tuple(d), (d[0],)], [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        return [inputs[0]], []

    def backward(self, params, out_grads, inputs, outputs):
        # One-vs-all hinge, matching the reference kernels
        # (src/operator/svm_output.cc:12-48): with s_j = +1 for the true
        # class and -1 otherwise,
        #   L1: grad_j = -s_j * reg * 1[margin - s_j x_j > 0]
        #   L2: grad_j = -2 s_j * reg * max(margin - s_j x_j, 0)
        x, label = inputs[0], inputs[1]
        lab = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lab, x.shape[1], dtype=x.dtype)
        sign = 2 * onehot - 1
        slack = params.margin - sign * x
        if params.use_linear:
            g = -sign * jnp.where(slack > 0, 1.0, 0.0)
        else:
            g = -2 * sign * jnp.maximum(slack, 0)
        g = g * params.regularization_coefficient
        return [g.astype(x.dtype), jnp.zeros_like(label)]


class IdentityAttachKLSparseRegParam(Params):
    sparseness_target = field(float, default=0.1)
    penalty = field(float, default=0.001)
    momentum = field(float, default=0.9)


@register_op("IdentityAttachKLSparseReg")
class IdentityAttachKLSparseRegOp(OpDef):
    """Identity with KL sparsity penalty gradient
    (identity_attach_KL_sparse_reg-inl.h); moving average of mean
    activation kept in an aux state."""

    param_cls = IdentityAttachKLSparseRegParam
    is_loss = False

    def list_auxiliary_states(self, params):
        return ["moving_avg"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        return list(in_shapes), [tuple(d)], [(1,)]

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        avg = aux[0]
        if train:
            m = params.momentum
            new_avg = m * avg + (1 - m) * jnp.mean(x).reshape(1)
            return [x], [jax.lax.stop_gradient(new_avg)]
        return [x], [avg]


# -- CTC loss (warpctc plugin parity) ----------------------------------------
class CTCLossParam(Params):
    use_data_lengths = field(bool, default=False)
    use_label_lengths = field(bool, default=False)
    blank_label = field(str, default="first", enum=("first", "last"))
    padding_mask = field(float, default=-1.0)


def _ctc_single(log_probs, labels, data_len, label_len, blank):
    """Negative log likelihood of one (T, C) log-prob sequence under CTC.

    Log-space alpha recursion (forward algorithm) as a lax.scan over
    time — the scan keeps the whole loss one fused XLA loop (TPU-native
    stand-in for the warp-ctc CUDA kernels, plugin/warpctc/)."""
    T, C = log_probs.shape
    L = labels.shape[0]
    S = 2 * L + 1
    neg_inf = jnp.asarray(-1e30, log_probs.dtype)
    labels = labels.astype(jnp.int32)
    # extended sequence: blank, l1, blank, l2, ..., blank
    z = jnp.full((S,), blank, jnp.int32).at[1::2].set(labels)
    s_idx = jnp.arange(S)
    valid_s = s_idx < 2 * label_len + 1
    # skip transition allowed where z_s != blank and z_s != z_{s-2}
    z_shift2 = jnp.concatenate([jnp.full((2,), -1, jnp.int32), z[:-2]])
    can_skip = (z != blank) & (z != z_shift2)

    alpha0 = jnp.full((S,), neg_inf)
    alpha0 = alpha0.at[0].set(log_probs[0, blank])
    alpha0 = alpha0.at[1].set(
        jnp.where(label_len > 0, log_probs[0, z[1]], neg_inf))
    alpha0 = jnp.where(valid_s, alpha0, neg_inf)

    def step(alpha, t):
        lp = log_probs[t]
        a1 = alpha
        a2 = jnp.concatenate([jnp.full((1,), neg_inf), alpha[:-1]])
        a3 = jnp.where(can_skip,
                       jnp.concatenate([jnp.full((2,), neg_inf), alpha[:-2]]),
                       neg_inf)
        new = jnp.logaddexp(jnp.logaddexp(a1, a2), a3) + lp[z]
        new = jnp.where(valid_s, new, neg_inf)
        new = jnp.where(t < data_len, new, alpha)  # frozen past seq end
        return new, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
    end = 2 * label_len  # index of final blank in the effective sequence
    a_last = alpha[end]
    a_prev = jnp.where(label_len > 0, alpha[jnp.maximum(end - 1, 0)], neg_inf)
    return -jnp.logaddexp(a_last, a_prev)


@register_op("CTCLoss", aliases=("ctc_loss", "WarpCTC", "_contrib_CTCLoss"))
class CTCLossOp(OpDef):
    """Connectionist Temporal Classification loss (plugin/warpctc/
    warpctc-inl.h capability, API shape of contrib CTCLoss).

    inputs: data (T, N, C) unnormalized activations, label (N, L)
    padded with ``padding_mask`` (or exact with use_label_lengths),
    plus optional data_lengths (N,) / label_lengths (N,).
    output: loss (N,).  Backward is the exact CTC gradient wrt data,
    obtained by differentiating the fused scan.
    """

    param_cls = CTCLossParam
    is_loss = True

    def list_arguments(self, params):
        args = ["data", "label"]
        if params.use_data_lengths:
            args.append("data_lengths")
        if params.use_label_lengths:
            args.append("label_lengths")
        return args

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("CTCLoss: data shape unknown")
        T, N, C = d
        lab = in_shapes[1] or (N, max(T // 2, 1))
        shapes = [tuple(d), tuple(lab)]
        if params.use_data_lengths:
            shapes.append((N,))
        if params.use_label_lengths:
            shapes.append((N,))
        return shapes, [(N,)], []

    def _compute(self, params, inputs):
        data, label = inputs[0], inputs[1]
        T, N, C = data.shape
        blank = 0 if params.blank_label == "first" else C - 1
        log_probs = jax.nn.log_softmax(data, axis=-1)  # (T, N, C)
        idx = 2
        if params.use_data_lengths:
            data_lens = inputs[idx].astype(jnp.int32)
            idx += 1
        else:
            data_lens = jnp.full((N,), T, jnp.int32)
        if params.use_label_lengths:
            label_lens = inputs[idx].astype(jnp.int32)
        else:
            label_lens = jnp.sum(label != params.padding_mask,
                                 axis=1).astype(jnp.int32)
        per_sample = jax.vmap(_ctc_single, in_axes=(1, 0, 0, 0, None))
        return per_sample(log_probs, jnp.maximum(label, 0), data_lens,
                          label_lens, blank)

    def forward(self, params, inputs, aux, train, key):
        return [self._compute(params, inputs)], []

    def backward(self, params, out_grads, inputs, outputs):
        grad = jax.grad(
            lambda d: jnp.sum(self._compute(params, [d] + list(inputs[1:]))))(
                inputs[0])
        return [grad] + [jnp.zeros_like(x) for x in inputs[1:]]


def _softmax_cross_entropy(data, label):
    # loss_binary_op-inl.h:35-70: scalar output sum_i -log(max(p_i[y_i], 1e-8))
    prob = jax.nn.softmax(data, axis=-1)
    picked = jnp.take_along_axis(
        prob, label.astype(jnp.int32)[:, None], axis=-1)[:, 0]
    return jnp.sum(-jnp.log(jnp.maximum(picked, 1e-8))).reshape(1)


def _softmax_cross_entropy_shape(params, in_shapes):
    d, l = in_shapes
    if d is None:
        raise ValueError("softmax_cross_entropy: data shape unknown")
    if len(d) != 2 or (l is not None and (len(l) != 1 or l[0] != d[0])):
        raise ValueError("softmax_cross_entropy: data must be 2D, label 1D "
                         "with matching dim0")
    return [d, (d[0],)], (1,)


def _softmax_cross_entropy_backward(params, out_grads, inputs, outputs):
    # loss_binary_op-inl.h:73-99: data_grad = scale * (softmax - onehot);
    # label is non-differentiable (kNullOp enforced in the reference).
    data, label = inputs
    prob = jax.nn.softmax(data, axis=-1)
    onehot = jax.nn.one_hot(label.astype(jnp.int32), data.shape[-1],
                            dtype=prob.dtype)
    scale = out_grads[0].reshape(()).astype(prob.dtype)
    return [scale * (prob - onehot), jnp.zeros_like(label)]

register_simple_op("softmax_cross_entropy", _softmax_cross_entropy, nin=2,
                   shape_rule=_softmax_cross_entropy_shape,
                   backward_fn=_softmax_cross_entropy_backward)


class SoftmaxCELossParam(Params):
    grad_scale = field(float, default=1.0)
    ignore_label = field(float, default=-1.0)
    use_ignore = field(bool, default=False)
    normalization = field(str, default="null", enum=("null", "batch", "valid"),
                          doc="gradient normalization, mirroring "
                              "SoftmaxOutputParam so loss='ce' keeps the "
                              "effective gradient scale of loss='softmax'")
    out_grad = field(bool, default=False,
                     doc="scale the gradient by the incoming output "
                         "gradient (loss-layer contract: ignored by "
                         "default, like SoftmaxOutput)")


@register_op("SoftmaxCELoss", aliases=("softmax_ce_loss",))
class SoftmaxCELossOp(OpDef):
    """Fused cross-entropy head: per-position NLL straight from logits.

    ``SoftmaxOutput`` (the reference head) must emit the full (N, V)
    probability tensor as its output — at transformer vocabularies
    that is gigabytes of HBM write+read per step just to feed a scalar
    loss.  This head outputs the (N,) losses instead
    (loss = logsumexp(x) - x[label], f32) and recomputes
    softmax(x) - onehot in backward from the logits it already has —
    no (N, V) output, no probability residual.  Opt-in via
    ``models.gpt(loss="ce")``; SoftmaxOutput stays the default for
    reference-parity semantics (probabilities as outputs).
    """

    param_cls = SoftmaxCELossParam
    is_loss = True

    def list_arguments(self, params):
        return ["data", "label"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("SoftmaxCELoss: data shape unknown")
        if len(d) != 2:
            raise ValueError(
                f"SoftmaxCELoss: data must be (N, V) logits, got {d}")
        return [tuple(d), (d[0],)], [(d[0],)], []

    def forward(self, params, inputs, aux, train, key):
        x, label = inputs
        xf = x.astype(jnp.float32)
        lab = label.astype(jnp.int32)
        lse = jax.scipy.special.logsumexp(xf, axis=-1)
        picked = jnp.take_along_axis(xf, lab[:, None], axis=-1)[:, 0]
        loss = lse - picked
        if params.use_ignore:
            loss = jnp.where(lab == int(params.ignore_label), 0.0, loss)
        return [loss], []

    def backward(self, params, out_grads, inputs, outputs):
        x, label = inputs
        lab = label.astype(jnp.int32)
        prob = jax.nn.softmax(x.astype(jnp.float32), axis=-1)
        grad = prob - jax.nn.one_hot(lab, x.shape[-1], dtype=prob.dtype)
        mask = None
        if params.use_ignore:
            mask = (lab != int(params.ignore_label))
            grad = grad * mask[:, None]
        # same semantics as SoftmaxOutput's non-multi-output branch
        # (softmax_output-inl.h): valid divides by the non-ignored count,
        # batch by dim0; the loss output itself is never normalized
        if params.normalization == "valid":
            valid = (jnp.maximum(jnp.sum(mask), 1).astype(grad.dtype)
                     if mask is not None else float(lab.size))
            grad = grad / valid
        elif params.normalization == "batch":
            grad = grad / x.shape[0]
        if params.out_grad and out_grads and out_grads[0] is not None:
            grad = grad * out_grads[0].astype(grad.dtype)[:, None]
        grad = grad * params.grad_scale
        return [grad.astype(x.dtype), jnp.zeros_like(label)]
