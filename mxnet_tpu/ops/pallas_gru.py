"""Fused GRU layer as Pallas TPU kernels.

Companion to ops/pallas_lstm.py (see its module docstring for the
design rationale): the whole time loop runs as one sequential grid with
the 3HxH recurrent weights and hidden state resident in VMEM, instead
of a `lax.scan` that re-streams the weights from HBM every step.  The
reference's fused-RNN coverage (cudnn_rnn-inl.h) includes GRU; this
completes the TPU-era equivalent for the second gated cell.

Gate math matches ops/rnn.py's scan cell exactly (r/z/n order, reset
gate applied to the hidden projection before tanh — the cuDNN/linear-
before-reset variant):

    hp = h @ Wh^T + bh;   r = sig(rx + hp_r);  z = sig(zx + hp_z)
    n  = tanh(nx + r * hp_n);   h' = (1 - z) * n + z * h

Forward saves (r, z, n, hp_n) per step; the reverse-streamed backward
kernel reconstructs every gradient from them with no recomputation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_util import idx32

from .pallas_lstm import _on_tpu, fused_lstm_eligible

__all__ = ["fused_gru", "fused_gru_eligible"]


def _sig(x):
    return jax.nn.sigmoid(x)


# -- forward ------------------------------------------------------------------

def _fwd_kernel(gx_ref, h0_ref, wh_ref, bh_ref, *refs, T, H, save):
    if save:
        ys_ref, hT_ref, acts_ref, h_sc = refs
    else:
        ys_ref, hT_ref, h_sc = refs
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_sc[:] = h0_ref[:].astype(jnp.float32)

    # recurrent matmul in the ACTIVATION dtype (bf16 MXU fast path),
    # keyed off gx like the flash kernels; carried state stays f32 in
    # scratch, accumulation f32 via preferred_element_type
    dt_lo = gx_ref.dtype
    hp = (jax.lax.dot_general(h_sc[:].astype(dt_lo),
                              wh_ref[:].astype(dt_lo),
                              (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
          + bh_ref[0].astype(jnp.float32))           # (N, 3H)
    gx = gx_ref[0].astype(jnp.float32)
    r = _sig(gx[:, 0 * H:1 * H] + hp[:, 0 * H:1 * H])
    z = _sig(gx[:, 1 * H:2 * H] + hp[:, 1 * H:2 * H])
    nh = hp[:, 2 * H:3 * H]
    n = jnp.tanh(gx[:, 2 * H:3 * H] + r * nh)
    h = (1.0 - z) * n + z * h_sc[:]
    if save:
        acts_ref[0] = jnp.concatenate([r, z, n, nh], axis=-1)
    ys_ref[0] = h.astype(ys_ref.dtype)
    h_sc[:] = h

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h.astype(hT_ref.dtype)


def _fwd(gx, h0, wh, bh, interpret, save):
    """``save=False`` skips the backward residuals (see pallas_lstm)."""
    T, N, G = gx.shape
    H = G // 3
    kernel = functools.partial(_fwd_kernel, T=T, H=H, save=save)
    full = idx32(lambda t: (0, 0))
    step3 = idx32(lambda t: (t, 0, 0))
    out_specs = [pl.BlockSpec((1, N, H), step3),
                 pl.BlockSpec((N, H), full)]
    out_shape = [jax.ShapeDtypeStruct((T, N, H), gx.dtype),   # ys
                 jax.ShapeDtypeStruct((N, H), gx.dtype)]      # hT
    if save:
        out_specs.append(pl.BlockSpec((1, N, 4 * H), step3))
        out_shape.append(
            jax.ShapeDtypeStruct((T, N, 4 * H), jnp.float32))  # r,z,n,nh
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, G), step3),
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((G, H), full),
            pl.BlockSpec((1, G), full),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((N, H), jnp.float32)],
        interpret=interpret,
    )(gx, h0, wh, bh)


# -- backward -----------------------------------------------------------------

def _bwd_kernel(acts_ref, hprev_ref, h0_ref, wh_ref, dys_ref, dhT_ref,
                dgx_ref, dwh_ref, dbh_ref, dh0_ref,
                dh_sc, dwh_sc, dbh_sc, *, T, H):
    rt = pl.program_id(0)
    t = T - 1 - rt

    @pl.when(rt == 0)
    def _():
        dh_sc[:] = dhT_ref[:].astype(jnp.float32)
        dwh_sc[:] = jnp.zeros_like(dwh_sc)
        dbh_sc[:] = jnp.zeros_like(dbh_sc)

    acts = acts_ref[0]
    r = acts[:, 0 * H:1 * H]
    z = acts[:, 1 * H:2 * H]
    n = acts[:, 2 * H:3 * H]
    nh = acts[:, 3 * H:4 * H]
    h_prev = jnp.where(t == 0, h0_ref[:].astype(jnp.float32),
                       hprev_ref[0].astype(jnp.float32))

    dh = dh_sc[:] + dys_ref[0].astype(jnp.float32)
    dz = dh * (h_prev - n)
    dn = dh * (1.0 - z)
    dn_pre = dn * (1.0 - n * n)
    dr = dn_pre * nh
    dnh = dn_pre * r
    dr_pre = dr * r * (1.0 - r)
    dz_pre = dz * z * (1.0 - z)
    dgates = jnp.concatenate([dr_pre, dz_pre, dn_pre], axis=-1)  # d gx
    dhp = jnp.concatenate([dr_pre, dz_pre, dnh], axis=-1)        # d hp

    dgx_ref[0] = dgates.astype(dgx_ref.dtype)
    # matmul operands in the activation dtype (MXU fast path, f32 acc)
    dt_lo = dgx_ref.dtype
    dhp_lo = dhp.astype(dt_lo)
    dwh_sc[:] += jax.lax.dot_general(dhp_lo, h_prev.astype(dt_lo),
                                     (((0,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
    dbh_sc[0, :] += jnp.sum(dhp, axis=0)
    dh_sc[:] = dh * z + jnp.dot(dhp_lo, wh_ref[:].astype(dt_lo),
                                preferred_element_type=jnp.float32)

    @pl.when(rt == T - 1)
    def _():
        dh0_ref[:] = dh_sc[:].astype(dh0_ref.dtype)
        dwh_ref[:] = dwh_sc[:].astype(dwh_ref.dtype)
        dbh_ref[0] = dbh_sc[0].astype(dbh_ref.dtype)


def _bwd_call(acts, ys, h0, wh, dys, dhT, out_dtype, interpret):
    T, N, _ = acts.shape
    H = ys.shape[-1]
    G = 3 * H
    kernel = functools.partial(_bwd_kernel, T=T, H=H)
    full = idx32(lambda rt: (0, 0))
    rev = idx32(lambda rt: (T - 1 - rt, 0, 0))
    rev_m1 = idx32(lambda rt: (jnp.maximum(T - 2 - rt, 0), 0, 0))
    return pl.pallas_call(
        kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, N, 4 * H), rev),    # acts[t]
            pl.BlockSpec((1, N, H), rev_m1),     # ys[t-1] == h_{t-1}
            pl.BlockSpec((N, H), full),
            pl.BlockSpec((G, H), full),
            pl.BlockSpec((1, N, H), rev),        # dys[t]
            pl.BlockSpec((N, H), full),
        ],
        out_specs=[
            pl.BlockSpec((1, N, G), rev),
            pl.BlockSpec((G, H), full),
            pl.BlockSpec((1, G), full),
            pl.BlockSpec((N, H), full),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, N, G), out_dtype),
            jax.ShapeDtypeStruct((G, H), jnp.float32),
            jax.ShapeDtypeStruct((1, G), jnp.float32),
            jax.ShapeDtypeStruct((N, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((N, H), jnp.float32),
            pltpu.VMEM((G, H), jnp.float32),
            pltpu.VMEM((1, G), jnp.float32),
        ],
        interpret=interpret,
    )(acts, ys, h0, wh, dys, dhT)


# -- public entry with custom VJP ---------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(gx, h0, wh, bh, interpret):
    # undifferentiated path (inference): no residual output
    ys, hT = _fwd(gx, h0, wh, bh, interpret, save=False)
    return ys, hT


def _fused_fwd(gx, h0, wh, bh, interpret):
    ys, hT, acts = _fwd(gx, h0, wh, bh, interpret, save=True)
    return (ys, hT), (acts, ys, h0, wh, bh)


def _fused_bwd(interpret, res, grads):
    acts, ys, h0, wh, bh = res
    dys, dhT = grads
    dgx, dwh, dbh, dh0 = _bwd_call(
        acts, ys, h0, wh, dys.astype(ys.dtype), dhT.astype(ys.dtype),
        ys.dtype, interpret)
    return (dgx, dh0.astype(h0.dtype), dwh.astype(wh.dtype),
            dbh.astype(bh.dtype))


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_gru_eligible(T, N, H, force=None):
    """Same gates as the LSTM kernel (alignment/VMEM rules are
    identical; the GRU weight block is smaller, so the LSTM bound is
    conservative)."""
    return fused_lstm_eligible(T, N, H, force=force)


def fused_gru(gx, h0, wh, bh, interpret=None):
    """One GRU layer over precomputed gate inputs.

    Args:
      gx: (T, N, 3H) input projection incl. input bias (x @ Wi^T + bi).
      h0: (N, H) initial state.
      wh: (3H, H) recurrent weights; bh: (3H,) recurrent bias.
      interpret: run through the Pallas interpreter (default: off-TPU).

    Returns ``(ys, hT)``; differentiable w.r.t. all four arrays.
    """
    if interpret is None:
        interpret = not _on_tpu()
    T, N, G = gx.shape
    H = G // 3
    if wh.shape != (G, H):
        raise ValueError(f"wh must be {(G, H)}, got {wh.shape}")
    return _fused(gx, h0.astype(jnp.float32), wh, bh.reshape(1, G),
                  bool(interpret))
