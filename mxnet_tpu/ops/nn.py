"""Neural-network layer operators.

Rebuild of the reference full-property operators (SURVEY.md §2.3):
FullyConnected (fully_connected-inl.h), Convolution/Deconvolution
(convolution-inl.h + cudnn_convolution-inl.h), Activation, LeakyReLU,
BatchNorm (batch_norm-inl.h), Pooling, Dropout, LRN, Embedding,
UpSampling, InstanceNorm, L2Normalization, SoftmaxActivation.

TPU-native lowering notes:
- Conv/Deconv/Pooling lower to ``lax.conv_general_dilated`` /
  ``lax.reduce_window`` — XLA tiles these onto the MXU directly; there is
  no im2col+gemm path nor cuDNN twin to maintain.
- BatchNorm keeps the reference's aux-state contract (moving_mean /
  moving_var updated during training, used in inference) via the op-level
  ``new_aux`` return; the executor commits aux updates after the step.
- Dropout consumes a PRNG key threaded through the executor
  (``need_rng``), replacing the reference's per-device Random resource
  (src/resource.cc:144-176).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..param import Params, field, tuple_of
from .op import OpDef, register_op


def _pair(t, n=2):
    if t is None:
        return (1,) * n
    if len(t) == 1:
        return t * n
    return tuple(t)


# -- FullyConnected ----------------------------------------------------------
class FullyConnectedParam(Params):
    num_hidden = field(int, required=True, lower=1)
    no_bias = field(bool, default=False)
    flatten = field(bool, default=True)


@register_op("FullyConnected")
class FullyConnectedOp(OpDef):
    """y = x @ W.T + b (reference fully_connected-inl.h; weight stored
    (num_hidden, input_dim) exactly like mshadow's dot(data, W.T))."""

    param_cls = FullyConnectedParam

    def list_arguments(self, params):
        return ["data", "weight"] if params.no_bias else ["data", "weight", "bias"]

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise ValueError("FullyConnected: data shape unknown")
        in_dim = int(np.prod(data[1:]))
        out = [data[0], params.num_hidden]
        completed = [tuple(data), (params.num_hidden, in_dim)]
        if not params.no_bias:
            completed.append((params.num_hidden,))
        return completed, [tuple(out)], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        w = inputs[1].astype(x.dtype)  # mixed-precision: follow activations
        x2 = x.reshape(x.shape[0], -1)
        y = jnp.dot(x2, w.T)
        if not params.no_bias:
            y = y + inputs[2].astype(x.dtype)
        return [y], []


# -- Convolution -------------------------------------------------------------
class ConvolutionParam(Params):
    kernel = field(tuple_of(int), required=True)
    num_filter = field(int, required=True, lower=1)
    stride = field(tuple_of(int), default=None)
    dilate = field(tuple_of(int), default=None)
    pad = field(tuple_of(int), default=None)
    num_group = field(int, default=1, lower=1)
    no_bias = field(bool, default=False)
    workspace = field(int, default=1024, doc="ignored (XLA owns scratch)")
    cudnn_tune = field(str, default=None, doc="ignored on TPU")
    cudnn_off = field(bool, default=False, doc="ignored on TPU")
    layout = field(str, default="NCHW", enum=("NCHW", "NHWC"))


def _conv_out_dim(d, k, s, p, dil):
    return (d + 2 * p - (dil * (k - 1) + 1)) // s + 1


@register_op("Convolution")
class ConvolutionOp(OpDef):
    """2D convolution (reference convolution-inl.h:489).

    Weight layout matches the reference: (num_filter, C/group, kH, kW).
    Lowered to lax.conv_general_dilated with feature_group_count; XLA maps
    it onto the MXU (no im2col materialization, no layout copies).
    """

    param_cls = ConvolutionParam

    def list_arguments(self, params):
        return ["data", "weight"] if params.no_bias else ["data", "weight", "bias"]

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise ValueError("Convolution: data shape unknown")
        nhwc = params.layout == "NHWC"
        n = data[0]
        c = data[3] if nhwc else data[1]
        ih, iw = (data[1], data[2]) if nhwc else (data[2], data[3])
        kh, kw = _pair(params.kernel)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        dh, dw = _pair(params.dilate)
        oh = _conv_out_dim(ih, kh, sh, ph, dh)
        ow = _conv_out_dim(iw, kw, sw, pw, dw)
        # weight layout is OIHW in both cases (reference checkpoint parity)
        wshape = (params.num_filter, c // params.num_group, kh, kw)
        out = ((n, oh, ow, params.num_filter) if nhwc
               else (n, params.num_filter, oh, ow))
        completed = [tuple(data), wshape]
        if not params.no_bias:
            completed.append((params.num_filter,))
        return completed, [out], []

    def forward(self, params, inputs, aux, train, key):
        x, w = inputs[0], inputs[1].astype(inputs[0].dtype)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        dh, dw = _pair(params.dilate)
        nhwc = params.layout == "NHWC"
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(sh, sw),
            padding=((ph, ph), (pw, pw)),
            rhs_dilation=(dh, dw),
            dimension_numbers=(("NHWC", "OIHW", "NHWC") if nhwc
                               else ("NCHW", "OIHW", "NCHW")),
            feature_group_count=params.num_group,
        )
        if not params.no_bias:
            b = inputs[2].astype(x.dtype)
            y = y + (b[None, None, None, :] if nhwc else b[None, :, None, None])
        return [y], []


class DeconvolutionParam(Params):
    kernel = field(tuple_of(int), required=True)
    num_filter = field(int, required=True, lower=1)
    stride = field(tuple_of(int), default=None)
    pad = field(tuple_of(int), default=None)
    adj = field(tuple_of(int), default=(0, 0))
    num_group = field(int, default=1)
    no_bias = field(bool, default=True)
    workspace = field(int, default=512)


@register_op("Deconvolution")
class DeconvolutionOp(OpDef):
    """Transposed convolution (reference deconvolution-inl.h); lowered as
    the gradient-of-conv via lhs dilation."""

    param_cls = DeconvolutionParam

    def list_arguments(self, params):
        return ["data", "weight"] if params.no_bias else ["data", "weight", "bias"]

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        n, c = data[0], data[1]
        kh, kw = _pair(params.kernel)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        ah, aw = _pair(params.adj, 2)
        if ah >= sh or aw >= sw:
            raise ValueError(
                f"Deconvolution adj {params.adj} must be smaller than "
                f"stride {(sh, sw)}")
        oh = sh * (data[2] - 1) + kh - 2 * ph + ah
        ow = sw * (data[3] - 1) + kw - 2 * pw + aw
        wshape = (c, params.num_filter // params.num_group, kh, kw)
        completed = [tuple(data), wshape]
        if not params.no_bias:
            completed.append((params.num_filter,))
        return completed, [(n, params.num_filter, oh, ow)], []

    def forward(self, params, inputs, aux, train, key):
        x, w = inputs[0], inputs[1].astype(inputs[0].dtype)
        kh, kw = _pair(params.kernel)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        ah, aw = _pair(params.adj, 2)
        # adjoint kernel: (cin, cout/g, kh, kw) -> (cout, cin/g, kh, kw),
        # in/out swapped within each group, spatially flipped
        g = params.num_group
        cin, cpg = w.shape[0], w.shape[1]
        wk = w.reshape(g, cin // g, cpg, kh, kw).swapaxes(1, 2)
        wk = jnp.flip(wk.reshape(g * cpg, cin // g, kh, kw), (-1, -2))
        y = lax.conv_general_dilated(
            x, wk,
            window_strides=(1, 1),
            padding=((kh - 1 - ph, kh - 1 - ph + ah),
                     (kw - 1 - pw, kw - 1 - pw + aw)),
            lhs_dilation=(sh, sw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=params.num_group,
        )
        if not params.no_bias:
            y = y + inputs[2][None, :, None, None]
        return [y], []


# -- Activation --------------------------------------------------------------
class ActivationParam(Params):
    act_type = field(str, required=True, enum=("relu", "sigmoid", "tanh", "softrelu"))


_ACTS = {
    "relu": lambda x: jnp.maximum(x, 0),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
}


@register_op("Activation")
class ActivationOp(OpDef):
    param_cls = ActivationParam

    def forward(self, params, inputs, aux, train, key):
        return [_ACTS[params.act_type](inputs[0])], []


class LeakyReLUParam(Params):
    act_type = field(str, default="leaky", enum=("leaky", "prelu", "elu", "rrelu"))
    slope = field(float, default=0.25)
    lower_bound = field(float, default=0.125)
    upper_bound = field(float, default=0.334)


@register_op("LeakyReLU")
class LeakyReLUOp(OpDef):
    param_cls = LeakyReLUParam
    need_rng = True

    def list_arguments(self, params):
        return ["data", "gamma"] if params.act_type == "prelu" else ["data"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if params.act_type == "prelu":
            return [tuple(d), (d[1],)], [tuple(d)], []
        return list(in_shapes), [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        t = params.act_type
        if t == "leaky":
            return [jnp.where(x > 0, x, params.slope * x)], []
        if t == "elu":
            return [jnp.where(x > 0, x, params.slope * (jnp.exp(x) - 1))], []
        if t == "prelu":
            g = inputs[1].reshape((1, -1) + (1,) * (x.ndim - 2))
            return [jnp.where(x > 0, x, g * x)], []
        # rrelu: random slope in train, mean slope in eval
        if train and key is not None:
            slope = jax.random.uniform(key, x.shape, x.dtype,
                                       params.lower_bound, params.upper_bound)
        else:
            slope = (params.lower_bound + params.upper_bound) / 2.0
        return [jnp.where(x > 0, x, slope * x)], []


# -- BatchNorm ---------------------------------------------------------------
class BatchNormParam(Params):
    eps = field(float, default=1e-3)
    momentum = field(float, default=0.9)
    fix_gamma = field(bool, default=True)
    use_global_stats = field(bool, default=False)
    axis = field(int, default=1, doc="channel axis (use -1 for NHWC)")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train(x, gamma, beta, axes, eps):
    """Fused training-mode batchnorm; returns (y, mean, var).

    mean/var are exposed for the moving-stat update (callers
    stop_gradient them; their cotangents are ignored in the VJP)."""
    (y, mean, var, _), _ = _bn_train_fwd(x, gamma, beta, axes, eps)
    return y, mean, var


def _bn_stats(x, axes, eps):
    """One-pass batch statistics: sibling sum/sum-of-squares reductions
    fuse into a single read of ``x`` (f32 accumulation over bf16 reads),
    where mean-then-variance would read the activations twice."""
    n = 1
    for i in axes:
        n *= x.shape[i]
    xf = x.astype(jnp.float32)
    s = jnp.sum(xf, axis=axes)
    s2 = jnp.sum(lax.square(xf), axis=axes)
    mean = s / n
    var = jnp.maximum(s2 / n - lax.square(mean), 0.0)
    inv = lax.rsqrt(var + eps)
    return mean, var, inv, n


def _bn_train_fwd(x, gamma, beta, axes, eps):
    mean, var, inv, _ = _bn_stats(x, axes, eps)
    ax = [i for i in range(x.ndim) if i not in axes]
    shape = tuple(x.shape[i] if i in ax else 1 for i in range(x.ndim))
    a = gamma.astype(jnp.float32) * inv
    b = beta.astype(jnp.float32) - mean * a
    y = (x.astype(jnp.float32) * a.reshape(shape) + b.reshape(shape)).astype(x.dtype)
    return (y, mean, var, inv), (x, gamma, mean, inv)


def _bn_train_bwd(axes, eps, res, cts):
    dy = cts[0]  # mean/var cotangents are zero (stop_gradient'd by callers)
    x, gamma, mean, inv = res
    ax = [i for i in range(x.ndim) if i not in axes]
    shape = tuple(x.shape[i] if i in ax else 1 for i in range(x.ndim))
    n = 1
    for i in axes:
        n *= x.shape[i]
    dyf = dy.astype(jnp.float32)
    xhat = (x.astype(jnp.float32) - mean.reshape(shape)) * inv.reshape(shape)
    # sibling reductions: one fused pass over (dy, x)
    dbeta = jnp.sum(dyf, axis=axes)
    dgamma = jnp.sum(dyf * xhat, axis=axes)
    a = (gamma.astype(jnp.float32) * inv).reshape(shape)
    dx = a * (dyf - dbeta.reshape(shape) / n - xhat * dgamma.reshape(shape) / n)
    return (dx.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


def _bn_train_vjp_fwd(x, gamma, beta, axes, eps):
    (y, mean, var, _), res = _bn_train_fwd(x, gamma, beta, axes, eps)
    return (y, mean, var), res


_bn_train.defvjp(_bn_train_vjp_fwd, _bn_train_bwd)


@register_op("BatchNorm", aliases=("CuDNNBatchNorm",))
class BatchNormOp(OpDef):
    """Batch normalization over axis 1 (reference batch_norm-inl.h:314).

    aux states: moving_mean, moving_var — updated with the reference's
    momentum rule during training; used directly when ``use_global_stats``
    or in inference mode.
    """

    param_cls = BatchNormParam

    def list_arguments(self, params):
        return ["data", "gamma", "beta"]

    def list_auxiliary_states(self, params):
        return ["moving_mean", "moving_var"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("BatchNorm: data shape unknown")
        c = (d[params.axis % len(d)],)
        return [tuple(d), c, c], [tuple(d)], [c, c]

    def forward(self, params, inputs, aux, train, key):
        x, gamma, beta = inputs
        moving_mean, moving_var = aux
        if params.fix_gamma:
            gamma = jnp.ones_like(gamma)
        ax = params.axis % x.ndim
        axes = tuple(i for i in range(x.ndim) if i != ax)
        shape = tuple(x.shape[i] if i == ax else 1 for i in range(x.ndim))
        if train and not params.use_global_stats:
            # fused path: one-pass stats + hand-written backward formula
            # (the cudnn_batch_norm-inl.h analog; autodiff through
            # mean/var costs several extra HBM passes over activations)
            y, mean, var = _bn_train(x, gamma, beta, axes, params.eps)
            m = params.momentum
            new_mean = (m * moving_mean + (1 - m) * mean).astype(moving_mean.dtype)
            new_var = (m * moving_var + (1 - m) * var).astype(moving_var.dtype)
            new_aux = [lax.stop_gradient(new_mean), lax.stop_gradient(new_var)]
            return [y], new_aux
        use_mean, use_var = moving_mean, moving_var
        new_aux = [moving_mean, moving_var]
        inv = lax.rsqrt(use_var.astype(jnp.float32) + params.eps)
        y = (x.astype(jnp.float32)
             - use_mean.astype(jnp.float32).reshape(shape)) * inv.reshape(shape)
        y = (y * gamma.astype(jnp.float32).reshape(shape)
             + beta.astype(jnp.float32).reshape(shape))
        return [y.astype(x.dtype)], new_aux


class InstanceNormParam(Params):
    eps = field(float, default=1e-3)


@register_op("InstanceNorm")
class InstanceNormOp(OpDef):
    param_cls = InstanceNormParam

    def list_arguments(self, params):
        return ["data", "gamma", "beta"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        c = (d[1],)
        return [tuple(d), c, c], [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x, gamma, beta = inputs
        axes = tuple(range(2, x.ndim))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.var(x, axis=axes, keepdims=True)
        shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
        y = (x - mean) * lax.rsqrt(var + params.eps)
        return [y * gamma.reshape(shape) + beta.reshape(shape)], []


class L2NormalizationParam(Params):
    eps = field(float, default=1e-10)
    mode = field(str, default="instance", enum=("instance", "channel", "spatial"))


@register_op("L2Normalization")
class L2NormalizationOp(OpDef):
    param_cls = L2NormalizationParam

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        if params.mode == "instance":
            axes = tuple(range(1, x.ndim))
        elif params.mode == "channel":
            axes = (1,)
        else:  # spatial
            axes = tuple(range(2, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=True) + params.eps)
        return [x / norm], []


# -- Pooling -----------------------------------------------------------------
class PoolingParam(Params):
    kernel = field(tuple_of(int), required=True)
    pool_type = field(str, default="max", enum=("max", "avg", "sum"))
    global_pool = field(bool, default=False)
    stride = field(tuple_of(int), default=None)
    pad = field(tuple_of(int), default=None)
    pooling_convention = field(str, default="valid", enum=("valid", "full"))
    layout = field(str, default="NCHW", enum=("NCHW", "NHWC"))


@register_op("Pooling")
class PoolingOp(OpDef):
    """Max/avg/sum pooling via lax.reduce_window (reference pooling-inl.h).

    Supports the reference's two output-size conventions: 'valid' (floor)
    and 'full' (ceil, the legacy mshadow convention used by LeNet-era
    models).
    """

    param_cls = PoolingParam

    def _geometry(self, params, h, w):
        kh, kw = _pair(params.kernel)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        if params.global_pool:
            return (h, w), (1, 1), (0, 0), (1, 1)
        rnd = np.ceil if params.pooling_convention == "full" else np.floor
        oh = int(rnd((h + 2 * ph - kh) / sh)) + 1
        ow = int(rnd((w + 2 * pw - kw) / sw)) + 1
        return (kh, kw), (sh, sw), (ph, pw), (oh, ow)

    def infer_shape(self, params, in_shapes):
        nhwc = params.layout == "NHWC"
        if nhwc:
            n, h, w, c = in_shapes[0]
        else:
            n, c, h, w = in_shapes[0]
        if params.global_pool:
            out = (n, 1, 1, c) if nhwc else (n, c, 1, 1)
            return list(in_shapes), [out], []
        _, _, _, (oh, ow) = self._geometry(params, h, w)
        out = (n, oh, ow, c) if nhwc else (n, c, oh, ow)
        return list(in_shapes), [out], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        nhwc = params.layout == "NHWC"
        h, w = (x.shape[1], x.shape[2]) if nhwc else (x.shape[2], x.shape[3])
        (kh, kw), (sh, sw), (ph, pw), (oh, ow) = self._geometry(params, h, w)
        # 'full' convention can need extra one-sided padding to reach (oh, ow).
        eh = max(0, (oh - 1) * sh + kh - h - 2 * ph)
        ew = max(0, (ow - 1) * sw + kw - w - 2 * pw)
        if nhwc:
            dims, strides = (1, kh, kw, 1), (1, sh, sw, 1)
            pads = ((0, 0), (ph, ph + eh), (pw, pw + ew), (0, 0))
        else:
            dims, strides = (1, 1, kh, kw), (1, 1, sh, sw)
            pads = ((0, 0), (0, 0), (ph, ph + eh), (pw, pw + ew))
        if params.pool_type == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
            if params.pool_type == "avg":
                y = y / (kh * kw)
        return [y.astype(x.dtype)], []


# -- Dropout -----------------------------------------------------------------
class DropoutParam(Params):
    p = field(float, default=0.5, lower=0.0, upper=1.0)


@register_op("Dropout")
class DropoutOp(OpDef):
    """Inverted dropout (reference dropout-inl.h); identity in inference."""

    param_cls = DropoutParam
    need_rng = True

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        if not train or params.p <= 0.0:
            return [x], []
        keep = 1.0 - params.p
        mask = jax.random.bernoulli(key, keep, x.shape)
        return [jnp.where(mask, x / keep, 0.0).astype(x.dtype)], []


# -- LRN ---------------------------------------------------------------------
class LRNParam(Params):
    nsize = field(int, required=True)
    alpha = field(float, default=1e-4)
    beta = field(float, default=0.75)
    knorm = field(float, default=2.0)


@register_op("LRN")
class LRNOp(OpDef):
    """Local response normalization across channels (lrn-inl.h)."""

    param_cls = LRNParam

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        sq = jnp.square(x)
        half = params.nsize // 2
        pad = [(0, 0), (half, params.nsize - half - 1), (0, 0), (0, 0)]
        acc = lax.reduce_window(jnp.pad(sq, pad), 0.0, lax.add,
                                (1, params.nsize, 1, 1), (1, 1, 1, 1),
                                [(0, 0)] * 4)
        scale = (params.knorm + params.alpha * acc / params.nsize) ** (-params.beta)
        return [x * scale], []


# -- Embedding ---------------------------------------------------------------
class EmbeddingParam(Params):
    input_dim = field(int, required=True, lower=1)
    output_dim = field(int, required=True, lower=1)


@register_op("Embedding")
class EmbeddingOp(OpDef):
    """Gather forward / scatter-add backward (embedding-inl.h).

    The backward comes for free from jax's gather vjp (a scatter-add),
    which XLA lowers natively.
    """

    param_cls = EmbeddingParam

    def list_arguments(self, params):
        return ["data", "weight"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("Embedding: data shape unknown")
        w = (params.input_dim, params.output_dim)
        return [tuple(d), w], [tuple(d) + (params.output_dim,)], []

    def infer_dtype(self, params, in_dtypes):
        """Output/weight type is the TABLE's type, never the index
        type: integer ids (the TPU-friendly input) must not leak int32
        into every downstream parameter through the default
        first-known-input rule."""
        w = in_dtypes[1] if in_dtypes[1] is not None else np.dtype(np.float32)
        d = in_dtypes[0] if in_dtypes[0] is not None else w
        return [d, w], [w], []

    def forward(self, params, inputs, aux, train, key):
        idx = inputs[0].astype(jnp.int32)
        return [jnp.take(inputs[1], idx, axis=0)], []


# -- UpSampling --------------------------------------------------------------
class UpSamplingParam(Params):
    scale = field(int, required=True, lower=1)
    sample_type = field(str, default="nearest", enum=("nearest", "bilinear"))
    num_args = field(int, default=1)
    num_filter = field(int, default=0)
    multi_input_mode = field(str, default="concat", enum=("concat", "sum"))
    workspace = field(int, default=512, doc="unused on TPU; kept for compat")


@register_op("UpSampling")
class UpSamplingOp(OpDef):
    param_cls = UpSamplingParam
    # reference upsampling.cc:58 set_key_var_num_args("num_args"): the
    # positional count fills num_args; bilinear mode IGNORES it for the
    # signature (ListArguments returns {data, weight} regardless,
    # upsampling-inl.h:180-189)
    key_var_num_args = "num_args"

    def list_arguments(self, params):
        if params.sample_type == "bilinear":
            return ["data", "weight"]
        return [f"arg{i}" for i in range(params.num_args)] if params.num_args > 1 else ["data"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        oh, ow = d[2] * params.scale, d[3] * params.scale
        multi = params.sample_type == "nearest" and params.num_args > 1
        if multi:
            for s in in_shapes:
                if s is None:
                    continue
                if oh % s[2] or ow % s[3]:
                    raise ValueError(
                        "UpSampling: input spatial size "
                        f"{(s[2], s[3])} must evenly divide the output "
                        f"{(oh, ow)} (= in0 * scale)")
        if multi and params.multi_input_mode == "sum":
            cs = {s[1] for s in in_shapes if s is not None}
            if len(cs) > 1:
                raise ValueError(
                    "UpSampling: number of channels must be the same "
                    f"when multi_input_mode=sum, got {sorted(cs)}")
            c = d[1]
        else:
            c = (sum(s[1] for s in in_shapes if s is not None)
                 if multi else d[1])
        completed = list(in_shapes)
        if params.sample_type == "bilinear":
            k = 2 * params.scale - params.scale % 2
            completed = [tuple(d), (d[1], 1, k, k)]
        return completed, [(d[0], c, oh, ow)], []

    def forward(self, params, inputs, aux, train, key):
        s = params.scale
        # multi-input: each input gets its own scale to reach the common
        # output size out_h = in0_h * scale (upsampling-inl.h:90, the
        # FCN-skip-connection pattern)
        oh, ow = inputs[0].shape[2] * s, inputs[0].shape[3] * s
        outs = []
        for x in (inputs if params.sample_type == "nearest" and params.num_args > 1
                  else inputs[:1]):
            if params.sample_type == "nearest":
                si, sj = oh // x.shape[2], ow // x.shape[3]
                y = jnp.repeat(jnp.repeat(x, si, axis=2), sj, axis=3)
            else:
                n, c, h, w = x.shape
                y = jax.image.resize(x, (n, c, h * s, w * s), method="bilinear")
            outs.append(y)
        if len(outs) > 1:
            # multi-input nearest: concat channels, or elementwise sum
            # (upsampling-inl.h up_enum::kSum)
            if params.multi_input_mode == "sum":
                return [functools.reduce(jnp.add, outs)], []
            return [jnp.concatenate(outs, axis=1)], []
        return [outs[0]], []


# -- SoftmaxActivation -------------------------------------------------------
class SoftmaxActivationParam(Params):
    mode = field(str, default="instance", enum=("instance", "channel"))


@register_op("SoftmaxActivation")
class SoftmaxActivationOp(OpDef):
    param_cls = SoftmaxActivationParam

    def forward(self, params, inputs, aux, train, key):
        axis = 1 if params.mode == "channel" else -1
        x = inputs[0]
        if params.mode == "instance" and x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        return [jax.nn.softmax(x, axis=axis)], []
