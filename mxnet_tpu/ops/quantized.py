"""Quantized inference operators (beyond the 2016 reference, which has
no quantization story; later MXNet grew contrib/quantization — this is
the TPU-native version of that capability).

Two execution modes per op, chosen by whether an activation scale was
calibrated:

- weight-only (``act_scale == 0``): int8 weights dequantize on the fly
  and the matmul runs in the activation dtype — 4x smaller/faster
  weight reads (HBM-bandwidth win), bit-identical activation math.
- full int8 (``act_scale > 0``): activations quantize per tensor,
  the MXU runs an int8 x int8 -> int32 contraction (double the int8
  throughput of bf16 on v5e+), and the result rescales by
  ``act_scale * per-channel weight scale``.

Weights are stored transposed-quantized with PER-OUTPUT-CHANNEL scales
(the standard accuracy-preserving choice; a whole-tensor scale loses
~1 bit of effective precision on typical layers).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from jax import lax

from ..param import Params, field, tuple_of
from .nn import _pair
from .op import OpDef, register_op


class QuantizedFullyConnectedParam(Params):
    num_hidden = field(int, required=True, lower=1)
    no_bias = field(bool, default=False)
    flatten = field(bool, default=True)
    act_scale = field(float, default=0.0,
                      doc="calibrated activation scale; 0 = weight-only")


@register_op("QuantizedFullyConnected")
class QuantizedFullyConnectedOp(OpDef):
    """y = x @ (w_int8 * wscale).T + b, optionally with the x-side also
    int8-quantized so the contraction itself runs on int8 (see module
    docstring).  Inference-oriented: round() has zero gradient."""

    param_cls = QuantizedFullyConnectedParam

    def list_arguments(self, params):
        args = ["data", "weight", "wscale"]
        if not params.no_bias:
            args.append("bias")
        return args

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise ValueError("QuantizedFullyConnected: data shape unknown")
        in_dim = int(np.prod(data[1:]))
        completed = [tuple(data), (params.num_hidden, in_dim),
                     (params.num_hidden,)]
        if not params.no_bias:
            completed.append((params.num_hidden,))
        return completed, [(data[0], params.num_hidden)], []

    def infer_dtype(self, params, in_dtypes):
        act = in_dtypes[0] or np.dtype(np.float32)
        ins = [act, np.dtype(np.int8), np.dtype(np.float32)]
        if not params.no_bias:
            ins.append(np.dtype(np.float32))
        return ins, [act], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        wq = inputs[1]
        wscale = inputs[2].astype(jnp.float32)
        x2 = x.reshape(x.shape[0], -1)
        if params.act_scale > 0.0:
            inv = 1.0 / params.act_scale
            xq = jnp.clip(jnp.round(x2.astype(jnp.float32) * inv),
                          -127, 127).astype(jnp.int8)
            y32 = lax.dot_general(xq, wq, (((1,), (1,)), ((), ())),
                                  preferred_element_type=jnp.int32)
            y = (y32.astype(jnp.float32)
                 * (params.act_scale * wscale)[None, :])
        else:
            w = wq.astype(x.dtype) * wscale.astype(x.dtype)[:, None]
            y = jnp.dot(x2, w.T).astype(jnp.float32)
        if not params.no_bias:
            y = y + inputs[-1].astype(jnp.float32)
        return [y.astype(x.dtype)], []


class QuantizedConvolutionParam(Params):
    kernel = field(tuple_of(int), required=True)
    num_filter = field(int, required=True, lower=1)
    stride = field(tuple_of(int), default=None)
    pad = field(tuple_of(int), default=None)
    no_bias = field(bool, default=False)
    layout = field(str, default="NCHW", enum=("NCHW", "NHWC"))
    act_scale = field(float, default=0.0)


@register_op("QuantizedConvolution")
class QuantizedConvolutionOp(OpDef):
    """Convolution with int8 weights + per-output-channel scales
    (weight-only dequant path; full int8 conv accumulate when a
    calibrated ``act_scale`` is present)."""

    param_cls = QuantizedConvolutionParam

    def list_arguments(self, params):
        args = ["data", "weight", "wscale"]
        if not params.no_bias:
            args.append("bias")
        return args

    def _wshape(self, params, in_ch):
        # weight layout is OIHW in BOTH layouts — exactly like the float
        # ConvolutionOp (ops/nn.py), so quantization is shape-preserving
        kh, kw = _pair(params.kernel)
        return (params.num_filter, in_ch, kh, kw)

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise ValueError("QuantizedConvolution: data shape unknown")
        n, h, w, c = ((data[0], data[1], data[2], data[3])
                      if params.layout == "NHWC"
                      else (data[0], data[2], data[3], data[1]))
        kh, kw = _pair(params.kernel)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        oh = (h + 2 * ph - kh) // sh + 1
        ow = (w + 2 * pw - kw) // sw + 1
        out = ((n, oh, ow, params.num_filter) if params.layout == "NHWC"
               else (n, params.num_filter, oh, ow))
        completed = [tuple(data), self._wshape(params, c),
                     (params.num_filter,)]
        if not params.no_bias:
            completed.append((params.num_filter,))
        return completed, [out], []

    def infer_dtype(self, params, in_dtypes):
        act = in_dtypes[0] or np.dtype(np.float32)
        ins = [act, np.dtype(np.int8), np.dtype(np.float32)]
        if not params.no_bias:
            ins.append(np.dtype(np.float32))
        return ins, [act], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        wq = inputs[1]
        wscale = inputs[2].astype(jnp.float32)
        sh, sw = _pair(params.stride)
        ph, pw = _pair(params.pad, 2) if params.pad else (0, 0)
        if params.layout == "NHWC":
            dn = lax.conv_dimension_numbers(x.shape, wq.shape,
                                            ("NHWC", "OIHW", "NHWC"))
            ch_axis = -1
        else:
            dn = lax.conv_dimension_numbers(x.shape, wq.shape,
                                            ("NCHW", "OIHW", "NCHW"))
            ch_axis = 1
        if params.act_scale > 0.0:
            inv = 1.0 / params.act_scale
            xq = jnp.clip(jnp.round(x.astype(jnp.float32) * inv),
                          -127, 127).astype(jnp.int8)
            y32 = lax.conv_general_dilated(
                xq, wq, (sh, sw), [(ph, ph), (pw, pw)],
                dimension_numbers=dn, preferred_element_type=jnp.int32)
            scale = params.act_scale * wscale
            shape = [1] * y32.ndim
            shape[ch_axis] = y32.shape[ch_axis]
            y = y32.astype(jnp.float32) * scale.reshape(shape)
        else:
            wshape = [1] * wq.ndim
            wshape[0] = wq.shape[0]  # O leads in both OHWI and OIHW
            w = wq.astype(x.dtype) * wscale.astype(x.dtype).reshape(wshape)
            y = lax.conv_general_dilated(
                x, w, (sh, sw), [(ph, ph), (pw, pw)],
                dimension_numbers=dn).astype(jnp.float32)
        if not params.no_bias:
            b = inputs[-1].astype(jnp.float32)
            shape = [1] * y.ndim
            shape[ch_axis] = b.shape[0]
            y = y + b.reshape(shape)
        return [y.astype(x.dtype)], []
