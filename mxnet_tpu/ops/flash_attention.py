"""Fused flash attention as a Pallas TPU kernel.

The hot op of the attention family (the reference framework predates
attention; its fused-kernel analog is the cuDNN RNN wrapper,
cudnn_rnn-inl.h — this is the TPU-era equivalent: hand-fused kernels
where stock XLA lowering leaves performance on the table).  Standard
streaming-softmax tiling: the (Sq x Sk) score matrix is never
materialized in HBM; each grid step loads one (block_q x d) Q tile and
one (block_k x d) K/V tile into VMEM, updates running max / sum-exp /
accumulator scratch, and writes the normalized output once on the last
K step.  MXU does the two matmuls per tile; accumulation is always
float32 regardless of input dtype.

Backward is a custom VJP with two more Pallas kernels (dQ, and dK/dV)
recomputing probabilities from the saved log-sum-exp — O(S) memory.
The log-sum-exp is also exposed as a differentiable output so ring
attention (parallel/ring_attention.py) can stream-combine per-shard
flash results with correct gradients.

``q_offset``/``k_offset`` shift the positions used by the causal mask,
which is what lets one kernel serve both local attention and one ring
step (global positions = shard offset + local positions).

``interpret=True`` (automatic off-TPU) runs the same kernels through the
Pallas interpreter so tests exercise identical code paths on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_util import idx32

__all__ = ["flash_attention", "flash_eligible", "gqa_group"]

# np.float32, not a Python float: inside Mosaic-lowered kernel bodies a
# bare Python float is a weak float64 constant, and Mosaic has no
# f64->f32 cast — the kernel would fail TPU lowering (caught by
# tests/test_perf_contract.py's cross-platform lowering gate)
_NEG_INF = np.float32(-1e30)
_ZERO = np.float32(0.0)
_TINY = np.float32(1e-30)


def _on_tpu():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def _fit_block(S, target):
    """Largest block <= target that divides S (halving — keeps the
    lane/sublane alignment of power-of-two targets)."""
    b = max(1, min(target, S))
    while b > 1 and S % b:
        b //= 2
    return b


def _block_sizes(Sq, Sk, block_q, block_k):
    """Resolve requested block sizes against the sequence lengths.
    Requested sizes are UPPER BOUNDS: measured on v5e, (512, 512) tiles
    run the fwd+bwd step ~4.6x faster than (128, 128) at S=2k (VMEM
    residency amortizes the HBM streams), so callers default high and
    this shrinks to fit shorter or non-multiple sequences.

    A fit that collapses below BOTH the request and MXU scale (e.g. 8
    for S=1000) would trip Mosaic's row-block tiling constraint or crawl
    through a 100x larger grid; the auto path pre-gates such shapes via
    :func:`flash_eligible`, and explicit ``impl="flash"`` callers get an
    actionable error instead of a degenerate kernel.  Deliberate small
    explicit blocks (tests, tiny shapes) stay allowed: the guard only
    fires when the fit shrank BELOW what the caller asked for."""
    bq, bk = _fit_block(Sq, block_q), _fit_block(Sk, block_k)
    if ((bq != Sq and bq < min(block_q, 128))
            or (bk != Sk and bk < min(block_k, 128))):
        raise ValueError(
            f"flash_attention: seq lens ({Sq}, {Sk}) admit no MXU-scale "
            f"block <= requested ({block_q}, {block_k}); fitted "
            f"({bq}, {bk}) — pad the sequence or pass explicit block "
            f"sizes that divide it")
    return bq, bk


def flash_eligible(Sq, Sk, block_q=512, block_k=512):
    """Whether the fused kernel is worth using for these sequence
    lengths: the fitted blocks must either cover the whole (short)
    sequence or stay MXU-scale (>= 128) — a degenerate fitted block
    (e.g. 8 for S=1000) would crawl; callers fall back to dense XLA."""
    bq, bk = _fit_block(Sq, block_q), _fit_block(Sk, block_k)
    return (bq == Sq or bq >= 128) and (bk == Sk or bk >= 128)


# ~16 MB VMEM per v5e core; leave headroom for Mosaic's own temporaries
_VMEM_BUDGET = 12 * 1024 * 1024


def _vmem_bytes(bq, bk, D, H, itemsize=4, Hkv=None):
    """Conservative per-grid-step VMEM footprint of the kernels: Q-class
    tiles (q, do) + K-class tiles (k, v, + pipelining slack), all
    double-buffered in the INPUT dtype (``itemsize`` — the kernels keep
    matmul operands native, so bf16 tiles are half the size), plus f32
    accumulator scratch and the f32 score tile.  An estimate, not
    Mosaic's allocator — it only needs to stop the block autofit from
    requesting tiles that cannot possibly fit."""
    Hf = 1 if H is None else H
    Hk = Hf if Hkv is None else Hkv                  # GQA: fewer kv heads
    tile = lambda blk, h: 2 * blk * h * D * itemsize  # double-buffered
    return (2 * tile(bq, Hf) + 3 * tile(bk, Hk)
            + 2 * Hf * max(bq, bk) * D * 4           # acc/dk/dv scratch
            + bq * bk * 4)                           # score tile


def _fit_vmem(bq, bk, Sq, Sk, D, H, itemsize=4, Hkv=None):
    """Halve the larger block (never below 128 or the whole-sequence
    tile) until the estimated footprint fits the VMEM budget.  The 512
    default was benchmarked on bhsd D=64 where it fits easily; bshd
    blocks span ALL heads, so high-H configs must scale back or Mosaic
    dies with an opaque allocation failure mid-train."""
    def shrinkable(b, S):
        return b > 128 and b == _fit_block(S, b)     # stays a divisor
    while _vmem_bytes(bq, bk, D, H, itemsize, Hkv) > _VMEM_BUDGET:
        if bk >= bq and shrinkable(bk, Sk):
            bk //= 2
        elif shrinkable(bq, Sq):
            bq //= 2
        elif shrinkable(bk, Sk):
            bk //= 2
        else:
            break                                    # floor: let Mosaic try
    # The floor zone (12-16 MB estimated) is left to Mosaic — the
    # estimate is conservative and small overshoots usually fit.  Past
    # physical VMEM the allocation CANNOT succeed; fail with the config
    # instead of Mosaic's opaque allocation error mid-train.
    if _vmem_bytes(bq, bk, D, H, itemsize, Hkv) > 16 * 1024 * 1024:
        raise ValueError(
            f"flash_attention: no block config fits VMEM (floor "
            f"block_q={bq}, block_k={bk} needs "
            f"~{_vmem_bytes(bq, bk, D, H, itemsize, Hkv) >> 20} MB for "
            f"D={D}, H={H}, kv_heads={Hkv}); use layout='bhsd' (per-head "
            f"tiles) or fall back to dense attention (impl='xla')")
    return bq, bk


def _mask_for(i, j, bq, bk, causal, qo, ko, window=0):
    """Score mask for Q tile i vs K tile j (True = keep); qo/ko are
    global position offsets (ring-step shards), possibly traced.
    ``window`` > 0 adds sliding-window locality: query q attends keys in
    (q - window, q] — Mistral-class local attention.  Tiles fully
    outside the band skip their COMPUTE (the FLOPs drop to
    O(S * window)); the grid still visits and fetches every K/V tile,
    so HBM traffic remains O(S^2 / bk) block fetches."""
    if not causal and not window:
        return None
    q_pos = qo + i * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    k_pos = ko + j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    if causal:
        keep = q_pos >= k_pos
        if window:
            keep = jnp.logical_and(keep, q_pos - k_pos < window)
        return keep
    # bidirectional window: exactly the symmetric band |q - k| < window
    return jnp.logical_and(q_pos - k_pos < window, k_pos - q_pos < window)


def _tile_live(i, j, bq, bk, causal, qo, ko, window=0):
    """Decorator: runs the tile body only when the (i, j) tile overlaps
    the live mask region — above-diagonal tiles (causal) and tiles
    entirely outside the sliding-window band contribute nothing, and
    skipping them is where the causal-FLOPs halving and the window's
    O(S * window) bound come from.  Unmasked bodies run
    unconditionally."""
    if not causal and not window:
        return lambda body: body()
    q_lo = qo + i * bq                 # first/last q position of the tile
    q_hi = q_lo + (bq - 1)
    k_lo = ko + j * bk
    k_hi = k_lo + (bk - 1)
    live = True
    if causal:
        live = jnp.logical_and(live, q_hi >= k_lo)
    if window:
        # any (q, k) in the tile with q - k < window (causal band) or
        # |q - k| < window (bidirectional)
        live = jnp.logical_and(live, q_lo - k_hi < window)
        if not causal:
            live = jnp.logical_and(live, k_lo - q_hi < window)
    return pl.when(live)


# -- forward ------------------------------------------------------------------
#
# Layout strategy (Mosaic tiling rule: the last two dims of every block
# must divide (8, 128) or equal the array dims):
#
# - BHSD: inputs flattened to (BH, S, D); grid (BH, nq, nk); blocks
#   (1, blk, D) — last two dims (blk, D) legal.  One head per grid row.
# - BSHD (sequence-major): the array stays (B, S, H, D) — blocks must
#   span the FULL (H, D) trailing dims to be legal, so the grid is
#   (B, nq, nk) and the kernel loops the (static, unrolled) head axis,
#   slicing each (blk, H, D) VMEM tile per head.  All head shuffling
#   happens in VMEM/registers: zero HBM activation transposes, which is
#   the point of the layout.
#
# Per-row tensors (lse/delta/dlse) are (BH, 1, S) [bhsd] or (B, H, S)
# [bshd] so their blocks' trailing dims can be 'equal' to the array's.


def _heads(H):
    return [None] if H is None else list(range(H))


def gqa_group(Hq, Hkv):
    """Validated grouped-query factor: q heads per shared K/V head.
    The single source of the 'multiple of kv heads' contract — every
    GQA entry point (kernel, op, ring, ulysses) validates through
    here so zero/non-multiple head counts fail identically."""
    if Hkv <= 0 or Hq % Hkv:
        raise ValueError(
            f"grouped-query attention: q heads ({Hq}) must be a "
            f"multiple of kv heads ({Hkv})")
    return Hq // Hkv


def _kv(h, group):
    """KV head for q-head ``h``: grouped-query attention maps ``group``
    consecutive q heads onto one shared K/V head (group == 1 = MHA)."""
    return h if h is None or group == 1 else h // group


def _load(ref, h):
    """(blk, D) tile in the INPUT dtype: 3D block (1, blk, D), or head
    ``h`` of a 4D (1, blk, H, D) block (static sublane index).

    No f32 upcast here: the MXU's fast path is bf16 x bf16 with float32
    accumulation (``preferred_element_type`` on every dot) — upcasting
    the operands would run the matmuls at the ~4x slower f32 MXU rate
    while gaining nothing the f32 accumulator doesn't already give."""
    x = ref[0]
    if h is not None:
        x = x[:, h, :]
    return x


def _store(ref, h, val):
    if h is None:
        ref[0] = val
    else:
        ref[0, :, h, :] = val


def _row(ref, h):
    """(blk,) row from a (1, 1, blk) [bhsd] or (1, H, blk) [bshd] block."""
    return ref[0, 0] if h is None else ref[0, h]


def _row_set(ref, h, val):
    if h is None:
        ref[0, 0] = val
    else:
        ref[0, h] = val


def _sget(ref, h):
    """Scratch slab: whole ref (bhsd) or leading-index ``h`` (bshd)."""
    return ref[...] if h is None else ref[h]


def _sset(ref, h, val):
    if h is None:
        ref[...] = val
    else:
        ref[h] = val


def _fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc, m_sc, l_sc, *, scale, causal, bq, bk, nk, H,
                window=0, group=1):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    i = pl.program_id(1)

    @_tile_live(i, j, bq, bk, causal, qo_ref[0, 0], ko_ref[0, 0], window)
    def _():
        mask = _mask_for(i, j, bq, bk, causal, qo_ref[0, 0], ko_ref[0, 0],
                         window)
        for h in _heads(H):
            q = _load(q_ref, h)
            k = _load(k_ref, _kv(h, group))
            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)

            m_prev = _sget(m_sc, h)[:, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_cur[:, None])
            if mask is not None:
                # without this, a fully-masked row (m_cur == _NEG_INF)
                # would get p == exp(0) == 1 for every masked entry
                p = jnp.where(mask, p, _ZERO)
            alpha = jnp.exp(m_prev - m_cur)
            l_cur = _sget(l_sc, h)[:, 0] * alpha + jnp.sum(p, axis=-1)
            v = _load(v_ref, _kv(h, group))
            # p cast DOWN to v's dtype so a bf16 input keeps the PV
            # matmul on the fast MXU path (f32 @ bf16 would promote v
            # and run the slow f32 pass); accumulation stays f32
            _sset(acc, h, _sget(acc, h) * alpha[:, None] + jnp.dot(
                p.astype(v.dtype), v, preferred_element_type=jnp.float32))
            _sset(m_sc, h, m_cur[:, None])
            _sset(l_sc, h, l_cur[:, None])

    @pl.when(j == nk - 1)
    def _():
        for h in _heads(H):
            l_row = _sget(l_sc, h)[:, 0]
            valid = l_row > _ZERO     # False only for fully-masked rows
            l_fin = jnp.maximum(l_row, _TINY)
            _store(o_ref, h,
                   jnp.where(valid[:, None], _sget(acc, h) / l_fin[:, None],
                             _ZERO).astype(o_ref.dtype))
            _row_set(lse_ref, h,
                     jnp.where(valid, _sget(m_sc, h)[:, 0] + jnp.log(l_fin),
                               _NEG_INF))


def _scalar_spec():
    return pl.BlockSpec((1, 1), idx32(lambda b, x, y: (0, 0)),
                        memory_space=pltpu.SMEM)


def _dims(q, k):
    """(BH, Sq, Sk, D, H) for a 3D (BH, S, D) [BHSD, flattened] or 4D
    (B, S, H, D) [BSHD] tensor pair.  H is None in the 3D case."""
    if q.ndim == 3:
        BH, Sq, D = q.shape
        return BH, Sq, k.shape[1], D, None
    B, Sq, H, D = q.shape
    return B * H, Sq, k.shape[1], D, H


def _seq_spec(blk, D, H, pick):
    """Block spec for a Q/K/V/dO-class tensor: BHSD (H=None) gets a
    (blk, D) tile of the flattened (BH, S, D) array per grid step; BSHD
    gets a (blk, H, D) tile spanning ALL heads (Mosaic requires full
    trailing (H, D) dims; the kernel head-loops in VMEM).  ``pick``
    selects which grid axis is this tensor's sequence block."""
    if H is None:
        return pl.BlockSpec((1, blk, D), idx32(lambda *g: (g[0], pick(g), 0)))
    return pl.BlockSpec((1, blk, H, D),
                        idx32(lambda *g: (g[0], pick(g), 0, 0)))


def _out_shape(BH, S, D, H, dtype):
    if H is None:
        return jax.ShapeDtypeStruct((BH, S, D), dtype)
    return jax.ShapeDtypeStruct((BH // H, S, H, D), dtype)


def _row_spec(blk, H, pick):
    """Block spec for an lse/delta-class per-row tensor, stored
    (BH, 1, S) [bhsd] or (B, H, S) [bshd]: Mosaic requires the last two
    block dims to divide (8, 128) or equal the array dims — a (1, blk)
    block of a 2D (BH, S) array fails that whenever BH > 1, so the row
    tensors carry a middle dim the block can be 'equal' on."""
    if H is None:
        return pl.BlockSpec((1, 1, blk), idx32(lambda *g: (g[0], 0, pick(g))))
    return pl.BlockSpec((1, H, blk), idx32(lambda *g: (g[0], 0, pick(g))))


def _row_shape(BH, S, H):
    if H is None:
        return (BH, 1, S)
    return (BH // H, H, S)


def _params(interpret):
    """Grid semantics: batch*head and q-block rows are independent
    (PARALLEL -> Mosaic may pipeline/reorder them); the k-block axis
    carries the running-softmax scratch state and must stay sequential
    (ARBITRARY).  Unsupported by the interpreter backend."""
    if interpret:
        return {}
    # renamed upstream: TPUCompilerParams (older jax) -> CompilerParams;
    # the string spellings parse in both generations, where the
    # pltpu.PARALLEL/ARBITRARY constants only exist in the newer one
    cp = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return {"compiler_params": cp(
        dimension_semantics=("parallel", "parallel", "arbitrary"))}


def _fwd(q, k, v, qo, ko, scale, causal, bq, bk, interpret, window=0):
    BH, Sq, Sk, D, H = _dims(q, k)
    nq, nk = Sq // bq, Sk // bk
    # grouped-query attention (bshd only): K/V may carry fewer heads
    Hkv = None if H is None else k.shape[2]
    group = 1 if H is None else H // Hkv
    kernel = functools.partial(_fwd_kernel, scale=np.float32(scale),
                               causal=causal, bq=bq, bk=bk, nk=nk, H=H,
                               window=window, group=group)
    qi = lambda g: g[1]
    ki = lambda g: g[2]
    grid0 = BH if H is None else BH // H
    sc = (lambda *dims: pltpu.VMEM(dims, jnp.float32)) if H is None else (
        lambda *dims: pltpu.VMEM((H,) + dims, jnp.float32))
    o, lse = pl.pallas_call(
        kernel,
        grid=(grid0, nq, nk),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            _seq_spec(bq, D, H, qi),
            _seq_spec(bk, D, Hkv, ki),
            _seq_spec(bk, D, Hkv, ki),
        ],
        out_specs=[
            _seq_spec(bq, D, H, qi),
            _row_spec(bq, H, qi),
        ],
        out_shape=[
            _out_shape(BH, Sq, D, H, q.dtype),
            jax.ShapeDtypeStruct(_row_shape(BH, Sq, H), jnp.float32),
        ],
        scratch_shapes=[
            sc(bq, D),
            sc(bq, 1),
            sc(bq, 1),
        ],
        interpret=interpret,
        **_params(interpret),
    )(qo, ko, q, k, v)
    return o, lse.reshape(BH, Sq)


# -- backward -----------------------------------------------------------------

def _bwd_dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, dlse_ref, dq_ref, dq_acc, *, scale, causal,
                   bq, bk, nk, H, window=0, group=1):
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    i = pl.program_id(1)

    @_tile_live(i, j, bq, bk, causal, qo_ref[0, 0], ko_ref[0, 0], window)
    def _():
        mask = _mask_for(i, j, bq, bk, causal, qo_ref[0, 0], ko_ref[0, 0],
                         window)
        for h in _heads(H):
            q = _load(q_ref, h)
            k = _load(k_ref, _kv(h, group))
            v = _load(v_ref, _kv(h, group))
            do = _load(do_ref, h)
            lse = _row(lse_ref, h)
            delta = _row(delta_ref, h)
            dlse = _row(dlse_ref, h)

            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp(s - lse[:, None])
            if mask is not None:
                p = jnp.where(mask, p, _ZERO)  # fully-masked: lse=_NEG_INF
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            # ds from the o path (p*(dp - delta)) and the lse output (p*dlse)
            ds = p * (dp - delta[:, None] + dlse[:, None]) * scale
            # ds cast down to the input dtype for the same MXU-path
            # reason as p in the forward (standard flash bwd recipe)
            _sset(dq_acc, h, _sget(dq_acc, h) + jnp.dot(
                ds.astype(k.dtype), k, preferred_element_type=jnp.float32))

    @pl.when(j == nk - 1)
    def _():
        for h in _heads(H):
            _store(dq_ref, h, _sget(dq_acc, h).astype(dq_ref.dtype))


def _bwd_dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, dlse_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, bq, bk, nq, H, window=0, group=1):
    i = pl.program_id(2)  # q-block index (inner loop)

    @pl.when(i == 0)
    def _():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    j = pl.program_id(1)  # k-block index (outer)

    @_tile_live(i, j, bq, bk, causal, qo_ref[0, 0], ko_ref[0, 0], window)
    def _():
        mask = _mask_for(i, j, bq, bk, causal, qo_ref[0, 0], ko_ref[0, 0],
                         window)
        for h in _heads(H):
            hk = _kv(h, group)
            q = _load(q_ref, h)
            k = _load(k_ref, hk)
            v = _load(v_ref, hk)
            do = _load(do_ref, h)
            lse = _row(lse_ref, h)
            delta = _row(delta_ref, h)
            dlse = _row(dlse_ref, h)

            s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                    preferred_element_type=jnp.float32) * scale
            if mask is not None:
                s = jnp.where(mask, s, _NEG_INF)
            p = jnp.exp(s - lse[:, None])
            if mask is not None:
                p = jnp.where(mask, p, _ZERO)  # fully-masked: lse=_NEG_INF
            # grouped-query attention: every q head of the group adds
            # into the SAME kv-head accumulator slab — the dK/dV sum
            # over the group happens right here in VMEM
            _sset(dv_acc, hk, _sget(dv_acc, hk) + jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
            dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                     preferred_element_type=jnp.float32)
            ds = p * (dp - delta[:, None] + dlse[:, None]) * scale
            _sset(dk_acc, hk, _sget(dk_acc, hk) + jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))

    @pl.when(i == nq - 1)
    def _():
        for hk in _heads(H if H is None else H // group):
            _store(dk_ref, hk, _sget(dk_acc, hk).astype(dk_ref.dtype))
            _store(dv_ref, hk, _sget(dv_acc, hk).astype(dv_ref.dtype))


def _bwd(scale, causal, bq, bk, interpret, window, res, g):
    q, k, v, qo, ko, o, lse = res
    do, dlse_in = g
    BH, Sq, Sk, D, H = _dims(q, k)
    nq, nk = Sq // bq, Sk // bk

    # do stays in the kernels' input dtype (bf16 on TPU): the dot with v
    # runs the fast MXU pass with f32 accumulation; only the rowwise
    # delta reduction upcasts (outside the kernels, O(S) not O(S^2))
    do = do.astype(q.dtype)
    dlse = (jnp.zeros_like(lse) if dlse_in is None
            else dlse_in.astype(jnp.float32))
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if H is not None:
        # (B, Sq, H) -> (B, H, Sq): the kernels' row layout; tiny (no D)
        delta = jnp.moveaxis(delta, 1, 2)
    # row tensors carry a middle dim for Mosaic (see _row_spec)
    row_shape = _row_shape(BH, Sq, H)
    lse = lse.reshape(row_shape)
    delta = delta.reshape(row_shape)
    dlse = dlse.reshape(row_shape)

    grid0 = BH if H is None else BH // H
    Hkv = None if H is None else k.shape[2]
    group = 1 if H is None else H // Hkv
    sc = (lambda *dims: pltpu.VMEM(dims, jnp.float32)) if H is None else (
        lambda *dims: pltpu.VMEM((H,) + dims, jnp.float32))
    qi = lambda g: g[1]
    ki = lambda g: g[2]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=np.float32(scale),
                          causal=causal, bq=bq, bk=bk, nk=nk, H=H,
                          window=window, group=group),
        grid=(grid0, nq, nk),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            _seq_spec(bq, D, H, qi),
            _seq_spec(bk, D, Hkv, ki),
            _seq_spec(bk, D, Hkv, ki),
            _seq_spec(bq, D, H, qi),
            _row_spec(bq, H, qi),
            _row_spec(bq, H, qi),
            _row_spec(bq, H, qi),
        ],
        out_specs=_seq_spec(bq, D, H, qi),
        out_shape=_out_shape(BH, Sq, D, H, q.dtype),
        scratch_shapes=[sc(bq, D)],
        interpret=interpret,
        **_params(interpret),
    )(qo, ko, q, k, v, do, lse, delta, dlse)

    qj = lambda g: g[2]
    kj = lambda g: g[1]
    sc_kv = sc if H is None else (
        lambda *dims: pltpu.VMEM((Hkv,) + dims, jnp.float32))
    BHkv = BH if H is None else (BH // H) * Hkv
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=np.float32(scale),
                          causal=causal, bq=bq, bk=bk, nq=nq, H=H,
                          window=window, group=group),
        grid=(grid0, nk, nq),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            _seq_spec(bq, D, H, qj),
            _seq_spec(bk, D, Hkv, kj),
            _seq_spec(bk, D, Hkv, kj),
            _seq_spec(bq, D, H, qj),
            _row_spec(bq, H, qj),
            _row_spec(bq, H, qj),
            _row_spec(bq, H, qj),
        ],
        out_specs=[
            _seq_spec(bk, D, Hkv, kj),
            _seq_spec(bk, D, Hkv, kj),
        ],
        out_shape=[
            _out_shape(BHkv, Sk, D, Hkv, k.dtype),
            _out_shape(BHkv, Sk, D, Hkv, v.dtype),
        ],
        scratch_shapes=[sc_kv(bk, D), sc_kv(bk, D)],
        interpret=interpret,
        **_params(interpret),
    )(qo, ko, q, k, v, do, lse, delta, dlse)
    return dq, dk, dv, None, None


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, qo, ko, scale, causal, bq, bk, interpret, window):
    return _fwd(q, k, v, qo, ko, scale, causal, bq, bk, interpret, window)


def _flash_fwd(q, k, v, qo, ko, scale, causal, bq, bk, interpret, window):
    o, lse = _fwd(q, k, v, qo, ko, scale, causal, bq, bk, interpret, window)
    return (o, lse), (q, k, v, qo, ko, o, lse)


_flash.defvjp(_flash_fwd, _bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512, q_offset=0, k_offset=0, return_lse=False,
                    interpret=None, layout="bhsd", window=0):
    """Fused multi-head attention: softmax(QK^T * scale) V.

    ``layout="bhsd"``: q (B, H, Sq, D), k/v (B, H, Sk, D) — the
    classic shape.  ``layout="bshd"``: q (B, Sq, H, D), k/v
    (B, Sk, H, D) — sequence-major, fed to the kernel with the head dim
    INDEXED in the block specs, so activations coming from a
    (B, S, D)-major transformer stack need no HBM transpose on the way
    in or out (the per-layer BSHD<->BHSD shuffles are the only
    activation transposes in the GPT train step's HLO).  Differentiable
    (custom VJP) either way; output matches the input layout.

    ``block_q``/``block_k`` are upper bounds; they shrink (by
    halving) to fit the sequence lengths.  ``window`` > 0 enables
    sliding-window (local) attention: each query sees keys within
    ``window`` positions (causal: the trailing band (q-window, q];
    bidirectional: |q-k| < window).  Tiles fully outside the band skip
    their matmuls — attention FLOPs drop to O(S * window) — though the
    grid still streams every K/V tile, so HBM traffic stays O(S^2/bk).
    ``q_offset``/``k_offset`` shift the causal-mask positions (may be
    traced values — used for ring-attention shards).  With
    ``return_lse`` the per-row log-sum-exp (B, H, Sq) float32 is also
    returned (differentiable).  Off-TPU the kernels run in the Pallas
    interpreter unless ``interpret`` is explicitly set.
    """
    if window < 0:
        raise ValueError(
            f"flash_attention: window must be >= 0 (got {window}); a "
            "negative band would mask every score")
    if layout == "bshd":
        B, Sq, H, D = q.shape
        Sk, Hkv = k.shape[1], k.shape[2]
    else:
        B, H, Sq, D = q.shape
        Sk, Hkv = k.shape[2], k.shape[1]
    if Hkv != H:
        # grouped-query / multi-query attention: `group` consecutive q
        # heads share one K/V head
        gqa_group(H, Hkv)
        if v.shape != k.shape:
            raise ValueError("flash_attention: k and v shapes must match")
    if scale is None:
        scale = float(1.0 / np.sqrt(D))
    if interpret is None:
        interpret = not _on_tpu()
    bq, bk = _block_sizes(Sq, Sk, block_q, block_k)
    bq, bk = _fit_vmem(bq, bk, Sq, Sk, D,
                       H if layout == "bshd" else None,
                       itemsize=jnp.dtype(q.dtype).itemsize,
                       Hkv=Hkv if layout == "bshd" else None)

    if layout == "bshd":
        qf, kf, vf = q, k, v              # native 4D, no data movement
        # (GQA handled natively: the kernels map q heads onto kv heads)
    else:
        if Hkv != H:
            # the flattened (BH, S, D) layout has no head axis for the
            # kernel to group on — expand K/V instead (correct, but the
            # traffic saving needs layout='bshd', where GQA is native)
            k = jnp.repeat(k, H // Hkv, axis=1)
            v = jnp.repeat(v, H // Hkv, axis=1)
        qf = q.reshape(B * H, Sq, D)
        kf = k.reshape(B * H, Sk, D)
        vf = v.reshape(B * H, Sk, D)
    if not causal and not window:
        # no mask consumes positions, so the offsets are inert — drop
        # them to constants.  More than hygiene: ring attention passes
        # axis_index-derived offsets, and XLA's SPMD partitioner
        # refuses a partition-id-rooted operand threaded into the
        # kernel call inside the ring's scan (PartitionId UNIMPLEMENTED
        # on CPU) when nothing in the kernel reads it.
        q_offset, k_offset = 0, 0
    qo = jnp.asarray(q_offset, jnp.int32).reshape(1, 1)
    ko = jnp.asarray(k_offset, jnp.int32).reshape(1, 1)
    o, lse = _flash(qf, kf, vf, qo, ko, scale, bool(causal), bq, bk,
                    bool(interpret), int(window))
    if layout != "bshd":
        o = o.reshape(B, H, Sq, D)
    if return_lse:
        return o, lse.reshape(B, H, Sq)
    return o
