"""Paged-attention decode as a Pallas TPU kernel.

The serving decode hot loop (``ops.attention.paged_attention``) is a
jnp gather + masked softmax: XLA materializes each request's whole
logical K/V view ``(B, S, Hkv, Dh)`` in HBM before attending, even
though a decode step only *reads* ``context_lens`` tokens of it.  This
kernel is the Mosaic follow-up the jnp docstring names: the grid walks
``(batch, table_slot)`` and streams ONE physical K/V block per step
from HBM into VMEM through the request's block table (scalar-prefetched
so the DMA's source index is known before the body runs — the
vLLM-PagedAttention formulation on TPU), updating flash-style running
max / sum-exp / f32 accumulators per kv head.  No gathered copy of the
cache ever exists; HBM traffic is exactly the live context bytes.

Grouped-query attention is native: the kernel loops the (static) kv
heads and each grid step's block fetch serves every q head of the
group — with int8 KV blocks (``k_scale``/``v_scale`` per-slot-per-head
f32 scales) the dequantize happens in VMEM, fused into the same pass,
so the HBM read is the int8 bytes.

Padded table rows point at the null block (id 0); their positions sit
at or beyond ``context_lens`` so the mask (and the compute-skip guard)
drops them, and a fully-empty row (``context_lens == 0``) never runs a
tile — its accumulator stays zero and the output is zeros, matching
the jnp path's empty-row guard.

``interpret=True`` (automatic off-TPU) runs the kernel through the
Pallas interpreter so the parity tests exercise the identical code
path on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..lint.annotations import hot_path
# the single eligibility definition lives with the dispatcher (which
# must be importable without Pallas); re-exported here for the tests
from .attention import paged_eligible  # noqa: F401
from .flash_attention import _on_tpu, gqa_group
from .pallas_util import idx32

__all__ = ["paged_attention_kernel", "paged_eligible"]

# np.float32, not Python floats: under jax_enable_x64 a bare literal in
# a Mosaic kernel body is a weak f64 constant with no f64->f32 cast
# (same rule as ops/flash_attention.py)
_NEG_INF = np.float32(-1e30)
_ZERO = np.float32(0.0)
_TINY = np.float32(1e-30)


def _kernel(bt_ref, ctx_ref, q_ref, k_ref, v_ref, *rest, scale, bs, nW,
            Hkv, group, window, quant):
    """One grid step (b, w): stream physical block ``bt[b, w]`` and
    fold its ``bs`` positions into the running softmax state of every
    kv head.  With ``quant`` the K/V refs are int8 and two
    per-slot-per-head scale refs follow them in the input list."""
    if quant:
        ksc_ref, vsc_ref, o_ref, acc, m_sc, l_sc = rest
    else:
        (o_ref, acc, m_sc, l_sc), ksc_ref, vsc_ref = rest, None, None
    b = pl.program_id(0)
    w = pl.program_id(1)

    @pl.when(w == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        m_sc[...] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[...] = jnp.zeros_like(l_sc)

    ctx = ctx_ref[b]
    base = w * bs
    # compute-skip: blocks entirely beyond the context (padded table
    # rows -> the null block) or entirely below the window band
    # contribute nothing; the DMA still ran, the math doesn't
    live = base < ctx
    if window:
        live = jnp.logical_and(live, base + bs > ctx - 1 - window)

    @pl.when(live)
    def _():
        pos = base + jax.lax.broadcasted_iota(jnp.int32, (group, bs), 1)
        keep = pos < ctx
        if window:
            keep = jnp.logical_and(keep, pos > ctx - 1 - window)
        for h in range(Hkv):
            k = k_ref[0, :, h, :]
            v = v_ref[0, :, h, :]
            if quant:
                # fused dequant in VMEM: the HBM stream was int8
                k = k.astype(jnp.float32) * ksc_ref[0, :, h][:, None]
                v = v.astype(jnp.float32) * vsc_ref[0, :, h][:, None]
            q = q_ref[0, h]                              # (group, Dh)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale
            s = jnp.where(keep, s, _NEG_INF)
            m_prev = m_sc[h, :, 0]
            m_cur = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.where(keep, jnp.exp(s - m_cur[:, None]), _ZERO)
            alpha = jnp.exp(m_prev - m_cur)
            l_cur = l_sc[h, :, 0] * alpha + jnp.sum(p, axis=-1)
            # p cast to v's dtype keeps a bf16 cache's PV matmul on the
            # fast MXU pass (dequantized int8 is already f32)
            acc[h] = acc[h] * alpha[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_sc[h, :, 0] = m_cur
            l_sc[h, :, 0] = l_cur

    @pl.when(w == nW - 1)
    def _():
        for h in range(Hkv):
            l_row = l_sc[h, :, 0]
            # a fully-masked row (context_lens == 0) accumulated
            # nothing: emit zeros, never 0/0 NaN
            valid = l_row > _ZERO
            l_fin = jnp.maximum(l_row, _TINY)
            o_ref[0, h] = jnp.where(valid[:, None],
                                    acc[h] / l_fin[:, None],
                                    _ZERO).astype(o_ref.dtype)


def _params(interpret):
    """Batch rows are independent (parallel); the table-slot axis
    carries the running-softmax scratch and must stay sequential."""
    if interpret:
        return {}
    cp = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams")
    return {"compiler_params": cp(
        dimension_semantics=("parallel", "arbitrary"))}


@hot_path
def paged_attention_kernel(q, k_cache, v_cache, block_tables,
                           context_lens, window=0, scale=None,
                           k_scale=None, v_scale=None, interpret=None):
    """Single-token paged decode attention, block-streamed.

    Same contract as ``ops.attention.paged_attention``: q ``(B, Hq,
    Dh)``, caches ``(num_blocks, block_size, Hkv, Dh)`` (int8 when
    ``k_scale``/``v_scale`` — ``(num_blocks, block_size, Hkv)`` f32 —
    are given), ``block_tables (B, W)`` int32 padded with the null
    block, ``context_lens (B,)``.  Returns ``(B, Hq, Dh)`` in q's
    dtype.  Empty rows (``context_lens == 0``) return zeros.
    """
    B, Hq, Dh = q.shape
    nb, bs, Hkv, _ = k_cache.shape
    if window < 0:
        raise ValueError(f"paged_attention: window must be >= 0 "
                         f"(got {window})")
    group = gqa_group(Hq, Hkv)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("paged_attention: k_scale and v_scale must be "
                         "given together (quantized K/V blocks carry "
                         "both)")
    quant = k_scale is not None
    scale = np.float32(scale if scale is not None else 1.0 / np.sqrt(Dh))
    if interpret is None:
        interpret = not _on_tpu()
    W = block_tables.shape[1]
    q4 = q.reshape(B, Hkv, group, Dh)

    def blk(*shape):
        """Whole-trailing-dims block (Mosaic: the last two block dims
        must divide the tile or equal the array dims — spanning the
        full (Hkv, Dh) / (Hkv,) trailing axes always satisfies it)."""
        return shape

    per_req = idx32(lambda b, w, bt, ctx: (b, 0, 0, 0))
    per_blk = idx32(lambda b, w, bt, ctx: (bt[b, w], 0, 0, 0))
    per_blk_sc = idx32(lambda b, w, bt, ctx: (bt[b, w], 0, 0))
    in_specs = [
        pl.BlockSpec(blk(1, Hkv, group, Dh), per_req),
        pl.BlockSpec(blk(1, bs, Hkv, Dh), per_blk),
        pl.BlockSpec(blk(1, bs, Hkv, Dh), per_blk),
    ]
    args = [q4, k_cache, v_cache]
    if quant:
        in_specs += [
            pl.BlockSpec(blk(1, bs, Hkv), per_blk_sc),
            pl.BlockSpec(blk(1, bs, Hkv), per_blk_sc),
        ]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, W),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(blk(1, Hkv, group, Dh), per_req),
        scratch_shapes=[
            pltpu.VMEM((Hkv, group, Dh), jnp.float32),
            pltpu.VMEM((Hkv, group, 1), jnp.float32),
            pltpu.VMEM((Hkv, group, 1), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, bs=bs, nW=W, Hkv=Hkv,
                          group=group, window=int(window), quant=quant),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, group, Dh), q.dtype),
        # mxtpu-lint: disable=host-sync (static host flag chosen at
        # trace time — never a device value, nothing to sync)
        interpret=bool(interpret),
        **_params(interpret),
    )(jnp.asarray(block_tables, jnp.int32),
      jnp.asarray(context_lens, jnp.int32), *args)
    return out.reshape(B, Hq, Dh)
