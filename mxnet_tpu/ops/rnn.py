"""Fused multi-layer RNN operator.

Rebuild of the reference ``RNN`` op (src/operator/rnn-inl.h:315 — CPU path
was LOG(FATAL), the real implementation was cuDNN v5 fused kernels,
src/operator/cudnn_rnn-inl.h:513).  TPU-native design:

- the whole sequence runs inside one ``lax.scan`` per layer/direction, so
  XLA compiles a single fused loop (the cuDNN-fused-kernel equivalent);
- the input projection ``x @ W_i2h^T`` for ALL timesteps is hoisted out
  of the scan into one big MXU matmul (time-batched), so the sequential
  part touches only the (N, H) @ (H, GH) recurrent matmul;
- parameters use the reference's concatenated flat-weight layout
  (cudnn_rnn-inl.h weight concat: all layer/direction W_i2h then W_h2h
  blocks, followed by all b_i2h then b_h2h blocks), so checkpoints keyed
  on a single ``parameters`` vector stay compatible in shape.

Gate orders follow cuDNN: LSTM (i, f, g, o), GRU (r, z, n).
Layout: data (T, N, input_size) time-major, states (L*D, N, H).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..param import Params, field
from .op import OpDef, register_op

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


class RNNParam(Params):
    state_size = field(int, required=True, lower=1)
    num_layers = field(int, required=True, lower=1)
    mode = field(str, required=True, enum=("rnn_relu", "rnn_tanh", "lstm", "gru"))
    bidirectional = field(bool, default=False)
    p = field(float, default=0.0, doc="dropout between layers")
    state_outputs = field(bool, default=False)


def _dirs(params):
    return 2 if params.bidirectional else 1


def _layer_input_size(params, input_size, layer):
    return input_size if layer == 0 else params.state_size * _dirs(params)


def _weight_size(params, input_size):
    """Total flat parameter count (mirrors cudnn_rnn-inl.h size calc)."""
    G, H, D = _GATES[params.mode], params.state_size, _dirs(params)
    total = 0
    for layer in range(params.num_layers):
        isz = _layer_input_size(params, input_size, layer)
        total += D * (G * H * isz + G * H * H)  # W_i2h + W_h2h
    total += params.num_layers * D * 2 * G * H  # b_i2h + b_h2h
    return total


def _slice_params(params, input_size, flat):
    """Split the flat vector into per-(layer, direction) weight blocks."""
    G, H, D = _GATES[params.mode], params.state_size, _dirs(params)
    out = []
    pos = 0
    for layer in range(params.num_layers):
        isz = _layer_input_size(params, input_size, layer)
        per_layer = []
        for d in range(D):
            wi = flat[pos:pos + G * H * isz].reshape(G * H, isz)
            pos += G * H * isz
            wh = flat[pos:pos + G * H * H].reshape(G * H, H)
            pos += G * H * H
            per_layer.append([wi, wh, None, None])
        out.append(per_layer)
    for layer in range(params.num_layers):
        for d in range(D):
            out[layer][d][2] = flat[pos:pos + G * H]
            pos += G * H
            out[layer][d][3] = flat[pos:pos + G * H]
            pos += G * H
    return out


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, inp):
            h, c = carry
            gx, wh, bh = inp  # gx: precomputed x-projection + b_i2h
            gates = gx + jnp.dot(h, wh.T) + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c2 = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h2 = jax.nn.sigmoid(o) * jnp.tanh(c2)
            return (h2, c2), h2
    elif mode == "gru":
        def step(carry, inp):
            h = carry
            gx, wh, bh = inp
            hp = jnp.dot(h, wh.T) + bh
            rx, zx, nx = jnp.split(gx, 3, axis=-1)
            rh, zh, nh = jnp.split(hp, 3, axis=-1)
            r = jax.nn.sigmoid(rx + rh)
            z = jax.nn.sigmoid(zx + zh)
            n = jnp.tanh(nx + r * nh)
            h2 = (1 - z) * n + z * h
            return h2, h2
    else:
        act = jnp.maximum if mode == "rnn_relu" else None

        def step(carry, inp):
            h = carry
            gx, wh, bh = inp
            pre = gx + jnp.dot(h, wh.T) + bh
            h2 = jnp.maximum(pre, 0) if mode == "rnn_relu" else jnp.tanh(pre)
            return h2, h2
    return step


def _fused_dispatch(mode, gx, h0, c0, wh, bh):
    """Route gated cells through their Pallas kernels (weights + state
    VMEM-resident for the whole sequence) when eligible; returns
    (ys, hT, cT-or-None), or None to use the scan fallback."""
    if mode not in ("lstm", "gru"):
        return None
    T, N, _ = gx.shape
    H = h0.shape[-1]
    if mode == "lstm":
        from .pallas_lstm import fused_lstm, fused_lstm_eligible

        if not fused_lstm_eligible(T, N, H):
            return None
        return fused_lstm(gx, h0, c0, wh, bh)
    from .pallas_gru import fused_gru, fused_gru_eligible

    if not fused_gru_eligible(T, N, H):
        return None
    ys, hT = fused_gru(gx, h0, wh, bh)
    return ys, hT, None


def _run_direction(mode, x, h0, c0, wi, wh, bi, bh, reverse):
    """One layer, one direction over the full sequence."""
    # time-batched input projection: (T, N, I) x (GH, I) -> (T, N, GH)
    gx = jnp.einsum("tni,gi->tng", x, wi) + bi
    if reverse:
        gx = jnp.flip(gx, axis=0)
    fused = _fused_dispatch(mode, gx, h0, c0, wh, bh)
    if fused is not None:
        ys, hT, cT = fused
        if reverse:
            ys = jnp.flip(ys, axis=0)
        return ys, hT, cT
    step = _cell_step(mode, h0.shape[-1])
    if mode == "lstm":
        (hT, cT), ys = lax.scan(lambda c, g: step(c, (g, wh, bh)), (h0, c0), gx)
    else:
        hT, ys = lax.scan(lambda c, g: step(c, (g, wh, bh)), h0, gx)
        cT = None
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, hT, cT


@register_op("RNN")
class RNNOp(OpDef):
    param_cls = RNNParam
    need_rng = True

    def list_arguments(self, params):
        args = ["data", "parameters", "state"]
        if params.mode == "lstm":
            args.append("state_cell")
        return args

    def list_outputs(self, params):
        outs = ["output"]
        if params.state_outputs:
            outs.append("state")
            if params.mode == "lstm":
                outs.append("state_cell")
        return outs

    def infer_shape(self, params, in_shapes):
        data = in_shapes[0]
        if data is None:
            raise ValueError("RNN: data shape unknown")
        T, N, input_size = data
        H, D, L = params.state_size, _dirs(params), params.num_layers
        wsize = _weight_size(params, input_size)
        state_shape = (L * D, N, H)
        completed = [tuple(data), (wsize,), state_shape]
        if params.mode == "lstm":
            completed.append(state_shape)
        outs = [(T, N, H * D)]
        if params.state_outputs:
            outs.append(state_shape)
            if params.mode == "lstm":
                outs.append(state_shape)
        return completed, outs, []

    def forward(self, params, inputs, aux, train, key):
        data, flat = inputs[0], inputs[1]
        h0_all = inputs[2]
        c0_all = inputs[3] if params.mode == "lstm" else None
        T, N, input_size = data.shape
        H, D, L = params.state_size, _dirs(params), params.num_layers
        blocks = _slice_params(params, input_size, flat)

        x = data
        hTs, cTs = [], []
        drop_keys = (jax.random.split(key, L) if key is not None else [None] * L)
        for layer in range(L):
            outs_dir = []
            for d in range(D):
                wi, wh, bi, bh = blocks[layer][d]
                h0 = h0_all[layer * D + d]
                c0 = c0_all[layer * D + d] if c0_all is not None else None
                ys, hT, cT = _run_direction(params.mode, x, h0, c0, wi, wh,
                                            bi, bh, reverse=(d == 1))
                outs_dir.append(ys)
                hTs.append(hT)
                if cT is not None:
                    cTs.append(cT)
            x = jnp.concatenate(outs_dir, axis=-1) if D == 2 else outs_dir[0]
            if params.p > 0 and train and layer < L - 1 and drop_keys[layer] is not None:
                keep = 1.0 - params.p
                mask = jax.random.bernoulli(drop_keys[layer], keep, x.shape)
                x = jnp.where(mask, x / keep, 0.0).astype(x.dtype)

        outputs = [x]
        if params.state_outputs:
            outputs.append(jnp.stack(hTs, axis=0))
            if params.mode == "lstm":
                outputs.append(jnp.stack(cTs, axis=0))
        return outputs, []
