"""Attention-era operators: LayerNorm, GELU, fused multi-head attention.

Beyond-parity additions (the 2016 reference predates transformers) that
make the Pallas flash-attention kernel (``ops/flash_attention.py``) and
a GPT-style model zoo entry (``models/transformer.py``) available from
the Symbol/NDArray frontends like any reference op.
"""

from __future__ import annotations

import contextlib
import contextvars
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..lint.annotations import hot_path
from ..param import Params, field
from .op import OpDef, register_op, register_simple_op

# Ambient SPMD context for the fused-attention op: Mosaic kernels cannot
# be auto-partitioned by GSPMD, so when a FlashAttention op runs inside
# a multi-device sharded program the kernel call must be wrapped in a
# shard_map over the batch axis (attention is embarrassingly parallel
# across data-parallel shards).  ShardedTrainer sets this around its
# traced graph calls; single-device programs never touch it.
_SPMD_ATTN = contextvars.ContextVar("spmd_attention", default=None)


@contextlib.contextmanager
def spmd_attention(mesh, batch_axis, seq_axis=None):
    """While active, FlashAttention ops adapt to the sharded program:

    - ``seq_axis`` sharded (sequence parallelism): the op routes to a
      sharded-attention schedule over that axis — ring (default) or
      Ulysses per the op's ``sp_impl`` param.  Per-shard local
      attention would silently attend within shards only, so SOME
      global schedule is required for correctness, whatever impl.
    - otherwise, batch sharded + Pallas path: the kernel call is
      wrapped in ``shard_map(..., in_specs=P(batch_axis, ...))`` so
      fused attention composes with data parallelism."""
    token = _SPMD_ATTN.set((mesh, batch_axis, seq_axis))
    try:
        yield
    finally:
        _SPMD_ATTN.reset(token)


# -- LayerNorm ---------------------------------------------------------------
class LayerNormParam(Params):
    axis = field(int, default=-1)
    eps = field(float, default=1e-5)


@register_op("LayerNorm", aliases=("layernorm",))
class LayerNormOp(OpDef):
    """Normalize over one axis with learnable scale/shift.

    Statistics are computed in f32 regardless of input dtype (bf16-safe,
    like the fused BatchNorm in ops/nn.py); XLA fuses the whole op into
    its neighbors.
    """

    param_cls = LayerNormParam

    def list_arguments(self, params):
        return ["data", "gamma", "beta"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("LayerNorm: data shape unknown")
        c = (d[params.axis % len(d)],)
        return [tuple(d), c, c], [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x, gamma, beta = inputs
        axis = params.axis % x.ndim
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=axis, keepdims=True)
        var = jnp.mean(jnp.square(xf - mean), axis=axis, keepdims=True)
        inv = jax.lax.rsqrt(var + params.eps)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        y = (xf - mean) * inv * gamma.astype(jnp.float32).reshape(shape) \
            + beta.astype(jnp.float32).reshape(shape)
        return [y.astype(x.dtype)], []


class RMSNormParam(Params):
    axis = field(int, default=-1)
    eps = field(float, default=1e-5)


@register_op("RMSNorm", aliases=("rmsnorm",))
class RMSNormOp(OpDef):
    """Root-mean-square normalization (llama-style LayerNorm without
    the mean subtraction or shift): y = x / rms(x) * gamma.  Stats in
    f32 like LayerNorm; XLA fuses it into its neighbors."""

    param_cls = RMSNormParam

    def list_arguments(self, params):
        return ["data", "gamma"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("RMSNorm: data shape unknown")
        c = (d[params.axis % len(d)],)
        return [tuple(d), c], [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        x, gamma = inputs
        axis = params.axis % x.ndim
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
        shape = [1] * x.ndim
        shape[axis] = x.shape[axis]
        y = xf * jax.lax.rsqrt(ms + params.eps) \
            * gamma.astype(jnp.float32).reshape(shape)
        return [y.astype(x.dtype)], []


register_simple_op(
    "gelu",
    lambda x: (0.5 * x.astype(jnp.float32)
               * (1.0 + jax.lax.erf(x.astype(jnp.float32)
                                    / np.sqrt(2.0)))).astype(x.dtype),
    nin=1)

# f32-activation convention like gelu: bf16 models must compute the
# swiglu gate identically in the training graph and the KV-cache
# decoder or near-tie logits round differently between them
register_simple_op(
    "silu",
    lambda x: (x.astype(jnp.float32)
               * jax.nn.sigmoid(x.astype(jnp.float32))).astype(x.dtype),
    nin=1)


# -- fused multi-head attention ----------------------------------------------
class FlashAttentionParam(Params):
    causal = field(bool, default=False)
    # sliding-window (local) attention radius; 0 = full attention
    # (negative values rejected at the kernel entry)
    window = field(int, default=0)
    block_q = field(int, default=512)
    block_k = field(int, default=512)
    impl = field(str, default="auto", enum=("auto", "flash", "xla"))
    layout = field(str, default="bhsd", enum=("bhsd", "bshd"))
    # sequence-parallel variant when the ambient seq axis is sharded:
    # ring (ppermute K/V shards; any head count) or ulysses (two
    # all-to-alls re-shard seq<->heads; needs heads % sp == 0)
    sp_impl = field(str, default="ring", enum=("ring", "ulysses"))


@register_op("FlashAttention", aliases=("flashattention",))
class FlashAttentionOp(OpDef):
    """softmax(Q K^T / sqrt(D)) V over (batch, heads, seq, head_dim)
    [layout='bhsd'] or (batch, seq, heads, head_dim) [layout='bshd',
    sequence-major — no activation transpose feeding the kernel].

    K/V may carry FEWER heads than Q (grouped-query / multi-query
    attention; q heads must be a multiple of kv heads): native in the
    Pallas kernels under layout='bshd' (one shared K/V head streamed
    per group), expanded under 'bhsd', the dense fallback, and the
    sequence-parallel schedules.  ``window`` > 0 adds sliding-window
    locality — including under sequence parallelism (ring masks with
    global positions and bounds its steps to the band; ulysses sees the
    full sequence after its all-to-all).  On TPU with fitting block
    sizes this lowers to the fused
    Pallas kernel (forward + custom-VJP backward); elsewhere it runs
    the XLA dense formulation.  Differentiable either way.
    """

    param_cls = FlashAttentionParam

    def list_arguments(self, params):
        return ["query", "key", "value"]

    def infer_shape(self, params, in_shapes):
        q = in_shapes[0]
        kv = in_shapes[1] or in_shapes[2]
        if q is None and kv is None:
            raise ValueError("FlashAttention: input shapes unknown")
        if q is None:
            q = kv
        if kv is None:
            kv = q          # MHA default; GQA needs k/v shapes known
        return [tuple(q), tuple(kv), tuple(kv)], [tuple(q)], []

    def forward(self, params, inputs, aux, train, key):
        q, k, v = inputs
        from .flash_attention import _on_tpu, flash_attention

        spmd = _SPMD_ATTN.get()
        mesh = batch_ax = None
        batch_sharded = False
        if spmd is not None:
            mesh, batch_ax, seq_ax = spmd
            mshape = dict(mesh.shape)
            batch_sharded = mshape.get(batch_ax, 1) > 1
            if seq_ax is not None and mshape.get(seq_ax, 1) > 1:
                # sequence-parallel program: global attention over the
                # sharded sequence REQUIRES a sharded schedule — local
                # per-shard attention would be silently wrong
                h_ax = 2 if params.layout == "bshd" else 1
                if k.shape[h_ax] != q.shape[h_ax]:
                    # grouped-query K/V under sequence parallelism:
                    # validate for a clean error here; ring streams the
                    # REDUCED K/V shards natively (bshd — bhsd expands
                    # inside the kernel call), ulysses keeps K/V native
                    # when kv heads divide the sp axis and expands at
                    # entry otherwise
                    from .flash_attention import gqa_group
                    gqa_group(q.shape[h_ax], k.shape[h_ax])
                if params.sp_impl == "ulysses":
                    from ..parallel.ulysses import ulysses_attention \
                        as sp_attention
                else:
                    from ..parallel.ring_attention import ring_attention \
                        as sp_attention

                out = sp_attention(
                    q, k, v, mesh, axis=seq_ax, causal=params.causal,
                    impl=params.impl, block_q=params.block_q,
                    block_k=params.block_k, layout=params.layout,
                    batch_axis=batch_ax if batch_sharded else None,
                    window=params.window)
                return [out], []

        seq_axis = 1 if params.layout == "bshd" else 2
        S = q.shape[seq_axis]
        from .flash_attention import flash_eligible
        use_flash = params.impl == "flash" or (
            params.impl == "auto" and _on_tpu()
            and flash_eligible(S, S, params.block_q, params.block_k))
        if use_flash:
            # wrap only when the BATCH axis is actually sharded: a
            # dp=1 x tp=N mesh must not funnel tp-sharded activations
            # through a batch-replicated shard_map (redundant compute +
            # resharding); with dp=1 the kernel call is single-program
            # per GSPMD and needs no wrap.  (A custom_partitioning rule
            # on flash_attention would decouple this from the trainer
            # entirely — candidate future work.)
            if batch_sharded:
                # data-parallel sharded program: run the kernel per
                # batch shard under shard_map (GSPMD cannot partition a
                # Mosaic custom call on its own)
                from jax.sharding import PartitionSpec

                from ..jax_compat import shard_map

                spec = PartitionSpec(batch_ax, *([None] * (q.ndim - 1)))

                def _local(q_s, k_s, v_s):
                    return flash_attention(q_s, k_s, v_s,
                                           causal=params.causal,
                                           block_q=params.block_q,
                                           block_k=params.block_k,
                                           layout=params.layout,
                                           window=params.window)

                out = shard_map(_local, mesh=mesh,
                                in_specs=(spec, spec, spec),
                                out_specs=spec, check_vma=False)(q, k, v)
                return [out], []
            out = flash_attention(q, k, v, causal=params.causal,
                                  block_q=params.block_q,
                                  block_k=params.block_k,
                                  layout=params.layout,
                                  window=params.window)
            return [out], []
        scale = 1.0 / np.sqrt(q.shape[-1])
        h_ax = 2 if params.layout == "bshd" else 1
        if k.shape[h_ax] != q.shape[h_ax]:
            # grouped-query attention through the dense path: expand K/V
            from .flash_attention import gqa_group
            rep = gqa_group(q.shape[h_ax], k.shape[h_ax])
            k = jnp.repeat(k, rep, axis=h_ax)
            v = jnp.repeat(v, rep, axis=h_ax)
        if params.layout == "bshd":
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
        else:
            s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        pos_q = jnp.arange(S)[:, None]
        pos_k = jnp.arange(S)[None, :]
        keep = None
        if params.causal:
            keep = pos_q >= pos_k
        if params.window < 0:
            raise ValueError(
                f"FlashAttention: window must be >= 0 "
                f"(got {params.window})")
        if params.window:
            band = pos_q - pos_k < params.window
            if not params.causal:
                band = jnp.logical_and(band, pos_k - pos_q < params.window)
            keep = band if keep is None else jnp.logical_and(keep, band)
        if keep is not None:
            s = jnp.where(keep, s, jnp.asarray(-jnp.inf, s.dtype))
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
        if params.layout == "bshd":
            return [jnp.einsum("bhqk,bkhd->bqhd", p, v)], []
        return [jnp.einsum("bhqk,bhkd->bhqd", p, v)], []


# -- paged attention (serving) -----------------------------------------------
def paged_eligible(block_size, head_dim):
    """Whether the Mosaic kernel's tile shapes are worth lowering for
    this cache geometry: head_dim should fill MXU/VPU lanes (multiples
    of 8 keep Mosaic's f32 tiling happy; 128 is the sweet spot) and the
    per-step K/V tile is one block, so a 1-token block would crawl
    through a 16x larger grid than the default geometry."""
    return head_dim % 8 == 0 and block_size >= 4


def resolve_paged_impl(block_size, head_dim, impl=None):
    """The implementation :func:`paged_attention` will trace for this
    cache geometry — ``"pallas"`` or ``"jnp"``.  Pure host logic (env +
    backend + eligibility), no Pallas import: callers that key compiled
    artifacts on the choice (serve.Engine's AOT fingerprint — an
    exported program bakes the lowering and replays it regardless of
    the env at load time) consult this without touching
    ``jax.experimental.pallas``."""
    if impl is None:
        impl = os.environ.get("MXTPU_PAGED_ATTENTION") or "auto"
    if impl not in ("auto", "pallas", "jnp"):
        raise ValueError(f"paged_attention: impl must be auto|pallas|jnp "
                         f"(got {impl!r})")
    if impl == "jnp":
        return "jnp"
    from .flash_attention import _on_tpu
    if impl == "pallas" or (_on_tpu()
                            and paged_eligible(block_size, head_dim)):
        return "pallas"
    return "jnp"


@hot_path
def paged_attention(q, k_cache, v_cache, block_tables, context_lens,
                    window=0, scale=None, k_scale=None, v_scale=None,
                    impl=None):
    """Single-token decode attention over a paged KV-cache.

    The serving engine (``mxnet_tpu/serve``) keeps one fixed
    device-resident cache carved into fixed-size blocks; each request
    owns a per-request *block table* mapping its logical token
    positions onto physical blocks.  Each query attends against its
    own context through the tables — the vLLM-style paged-attention
    formulation.  On TPU this dispatches to the Mosaic kernel in
    ``ops/pallas_paged_attention.py`` that streams K/V blocks from HBM
    with f32 accumulation (``impl="auto"`` default, overridable per
    process via ``MXTPU_PAGED_ATTENTION=auto|pallas|jnp`` — the same
    selection shape as ``flash_attention``); everywhere else it runs
    the XLA gather + masked softmax below, which doubles as the
    kernel's parity oracle.

    Args:
      q: (B, Hq, Dh) — one query token per sequence.
      k_cache/v_cache: (num_blocks, block_size, Hkv, Dh) physical
        cache.  Hq must be a multiple of Hkv (grouped-query native:
        kv head g serves q heads [g*group, (g+1)*group)).
      block_tables: (B, W) int32 physical block ids per sequence, in
        logical order; rows pad with the null block (id 0) past the
        sequence's last block.
      context_lens: (B,) int32 — valid cache entries per sequence
        (the current token's K/V already written).  Padded table
        entries sit beyond the context and are masked out.  A row with
        0 valid entries (a dead slot in a bucketed batch) returns
        zeros — never a fully-masked softmax's NaN.
      window: sliding-window radius (0 = full attention), matching
        the FlashAttention op's ``window`` semantics at decode: the
        query at position L-1 sees positions > L-1-window only.
      scale: score scale; default 1/sqrt(Dh).
      k_scale/v_scale: per-slot-per-head f32 dequantization scales
        (num_blocks, block_size, Hkv) for int8 K/V caches
        (``MXTPU_SERVE_KV_DTYPE=int8``): the cache entry is
        ``int8 * scale``.  Pass both or neither.
      impl: "auto" (kernel on TPU), "pallas", or "jnp"; default the
        ``MXTPU_PAGED_ATTENTION`` env var, else "auto".

    Returns (B, Hq, Dh) attention output in q's dtype.
    """
    B, Hq, Dh = q.shape
    nb, bs, Hkv, _ = k_cache.shape
    if window < 0:
        raise ValueError(f"paged_attention: window must be >= 0 "
                         f"(got {window})")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("paged_attention: k_scale and v_scale must be "
                         "given together")
    from .flash_attention import gqa_group
    group = gqa_group(Hq, Hkv)
    if resolve_paged_impl(bs, Dh, impl) == "pallas":
        # deferred import: impl="jnp" is the escape hatch when the
        # kernel (or jax.experimental.pallas itself) misbehaves, so it
        # must not require the Pallas modules to import
        from .pallas_paged_attention import paged_attention_kernel
        return paged_attention_kernel(
            q, k_cache, v_cache, block_tables, context_lens,
            window=window, scale=scale, k_scale=k_scale,
            v_scale=v_scale)
    scale = scale if scale is not None else 1.0 / np.sqrt(Dh)
    S = block_tables.shape[1] * bs
    # (B, W, bs, Hkv, Dh) -> (B, S, Hkv, Dh): each row's logical view
    k = k_cache[block_tables].reshape(B, S, Hkv, Dh)
    v = v_cache[block_tables].reshape(B, S, Hkv, Dh)
    if k_scale is not None:
        # int8 blocks dequantize through the same gathered view; the
        # scale arrays ride the same block tables (serve/engine.py owns
        # them alongside k_cache/v_cache)
        k = (k.astype(jnp.float32)
             * k_scale[block_tables].reshape(B, S, Hkv)[..., None]
             ).astype(q.dtype)
        v = (v.astype(jnp.float32)
             * v_scale[block_tables].reshape(B, S, Hkv)[..., None]
             ).astype(q.dtype)
    qg = q.reshape(B, Hkv, group, Dh)
    s = jnp.einsum("bkgd,bskd->bkgs", qg, k) * scale
    pos = jnp.arange(S)[None, :]
    keep = pos < context_lens[:, None]
    if window:
        keep = jnp.logical_and(keep,
                               pos > context_lens[:, None] - 1 - window)
    s = jnp.where(keep[:, None, None, :], s,
                  jnp.asarray(-jnp.inf, s.dtype))
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgs,bskd->bkgd", p, v)
    # an all-masked row's softmax is 0/0 = NaN: a bucketed batch's dead
    # slot (context_lens == 0) must yield zeros, or one padded row
    # poisons MXTPU_NUMERIC_WATCH's logits-finite flag for the batch
    out = jnp.where((context_lens > 0)[:, None, None, None], out,
                    jnp.zeros((), out.dtype))
    return out.reshape(B, Hq, Dh)


# -- rotary position embedding ------------------------------------------------
class RoPEParam(Params):
    base = field(float, default=10000.0)
    layout = field(str, default="bshd", enum=("bshd", "bhsd"))
    # global position of the first row — sequence-parallel shards and
    # autoregressive decode pass their offset, mirroring the flash
    # kernel's q_offset/k_offset contract
    offset = field(int, default=0)


@register_op("RoPE", aliases=("rope",))
class RoPEOp(OpDef):
    """Rotary position embedding (RoFormer; the long-context standard):
    rotates each head-dim pair (x_i, x_{i+D/2}) by pos * base^(-2i/D),
    making Q.K^T depend on relative position only.  Applied to Q and K
    after the head reshape — composes with FlashAttention in either
    layout, GQA (apply per tensor), and sequence shards via ``offset``.
    Elementwise cos/sin — XLA fuses it into the surrounding projections;
    no kernel needed.
    """

    param_cls = RoPEParam

    def list_arguments(self, params):
        return ["data"]

    def infer_shape(self, params, in_shapes):
        d = in_shapes[0]
        if d is None:
            raise ValueError("RoPE: data shape unknown")
        if d[-1] % 2:
            raise ValueError(f"RoPE: head_dim must be even, got {d[-1]}")
        return [tuple(d)], [tuple(d)], []

    def forward(self, params, inputs, aux, train, key):
        (x,) = inputs
        seq_axis = 1 if params.layout == "bshd" else 2
        S, D = x.shape[seq_axis], x.shape[-1]
        half = D // 2
        inv_freq = params.base ** (
            -jnp.arange(0, half, dtype=jnp.float32) / half)
        pos = jnp.arange(S, dtype=jnp.float32) + params.offset
        ang = pos[:, None] * inv_freq[None, :]          # (S, D/2)
        shape = [1] * x.ndim
        shape[seq_axis] = S
        shape[-1] = half
        cos = jnp.cos(ang).reshape(shape)
        sin = jnp.sin(ang).reshape(shape)
        xf = x.astype(jnp.float32)
        x1, x2 = xf[..., :half], xf[..., half:]
        out = jnp.concatenate([x1 * cos - x2 * sin,
                               x1 * sin + x2 * cos], axis=-1)
        return [out.astype(x.dtype)], []
