"""Matrix / shape-manipulation operators.

Rebuild of src/operator/matrix_op{.cc,-inl.h} (dot, batch_dot, transpose,
expand_dims, crop/slice, slice_axis, flip) plus the full-property shape
ops Reshape/Flatten/Concat/SliceChannel/SwapAxis/Cast/Pad
(src/operator/{reshape,concat,slice_channel,swapaxis,cast,pad}-inl.h).
``dot`` hits the MXU directly through jnp.dot / lax.dot_general.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import np_dtype
from ..param import Params, field, tuple_of
from .op import OpDef, register_op, register_simple_op


# -- dot / batch_dot ---------------------------------------------------------
class DotParam(Params):
    transpose_a = field(bool, default=False)
    transpose_b = field(bool, default=False)


def _dot_shape(params, in_shapes):
    a, b = in_shapes
    if a is None or b is None:
        raise ValueError("dot: input shapes unknown")
    am = a[::-1] if params.transpose_a else a
    bm = b[::-1] if params.transpose_b else b
    if len(a) == 1 and len(b) == 1:
        return in_shapes, (1,)
    if am[-1] != bm[0]:
        raise ValueError(f"dot: shape mismatch {a} x {b}")
    return in_shapes, tuple(am[:-1]) + tuple(bm[1:])


def _dot(p, a, b):
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b).reshape(1)
    am = a.T if p.transpose_a else a
    bm = b.T if p.transpose_b else b
    # Accumulate in f32 on the MXU regardless of input dtype.
    return jnp.dot(am, bm)


register_simple_op("dot", _dot, nin=2, param_cls=DotParam, shape_rule=_dot_shape)


def _batch_dot_shape(params, in_shapes):
    a, b = in_shapes
    am = (a[0], a[2], a[1]) if params.transpose_a else a
    bm = (b[0], b[2], b[1]) if params.transpose_b else b
    if am[0] != bm[0] or am[2] != bm[1]:
        raise ValueError(f"batch_dot: shape mismatch {a} x {b}")
    return in_shapes, (am[0], am[1], bm[2])


def _batch_dot(p, a, b):
    am = jnp.swapaxes(a, 1, 2) if p.transpose_a else a
    bm = jnp.swapaxes(b, 1, 2) if p.transpose_b else b
    return jnp.einsum("bij,bjk->bik", am, bm)


register_simple_op("batch_dot", _batch_dot, nin=2, param_cls=DotParam,
                   shape_rule=_batch_dot_shape)


# -- transpose / swapaxes / expand_dims / flip -------------------------------
class TransposeParam(Params):
    axes = field(tuple_of(int), default=None, doc="permutation; None reverses")


def _transpose_shape(p, in_shapes):
    s = in_shapes[0]
    axes = p.axes if p.axes else tuple(reversed(range(len(s))))
    return in_shapes, tuple(s[a] for a in axes)


register_simple_op("transpose", lambda p, x: jnp.transpose(x, p.axes or None),
                   nin=1, param_cls=TransposeParam, shape_rule=_transpose_shape)


class SwapAxisParam(Params):
    dim1 = field(int, default=0)
    dim2 = field(int, default=0)


def _swap_shape(p, in_shapes):
    s = list(in_shapes[0])
    s[p.dim1], s[p.dim2] = s[p.dim2], s[p.dim1]
    return in_shapes, tuple(s)


register_simple_op("SwapAxis", lambda p, x: jnp.swapaxes(x, p.dim1, p.dim2),
                   nin=1, param_cls=SwapAxisParam, shape_rule=_swap_shape,
                   aliases=("swapaxes",))


class ExpandDimsParam(Params):
    axis = field(int, required=True)


register_simple_op(
    "expand_dims", lambda p, x: jnp.expand_dims(x, p.axis), nin=1,
    param_cls=ExpandDimsParam,
    shape_rule=lambda p, s: (s, tuple(np.expand_dims(np.empty(s[0]), p.axis).shape)))


class FlipParam(Params):
    axis = field(int, required=True)


register_simple_op("flip", lambda p, x: jnp.flip(x, p.axis), nin=1,
                   param_cls=FlipParam, shape_rule="same")


# -- slice_axis / crop -------------------------------------------------------
class SliceAxisParam(Params):
    axis = field(int, required=True)
    begin = field(int, required=True)
    end = field(int, default=None, doc="None means to the end")


def _slice_axis_shape(p, in_shapes):
    s = list(in_shapes[0])
    ax = p.axis % len(s)
    begin = p.begin % s[ax] if p.begin < 0 else p.begin
    end = s[ax] if p.end is None else (p.end % s[ax] if p.end < 0 else p.end)
    s[ax] = end - begin
    return in_shapes, tuple(s)


def _slice_axis(p, x):
    ax = p.axis % x.ndim
    begin = p.begin % x.shape[ax] if p.begin < 0 else p.begin
    end = x.shape[ax] if p.end is None else (p.end % x.shape[ax] if p.end < 0 else p.end)
    idx = [slice(None)] * x.ndim
    idx[ax] = slice(begin, end)
    return x[tuple(idx)]


register_simple_op("slice_axis", _slice_axis, nin=1, param_cls=SliceAxisParam,
                   shape_rule=_slice_axis_shape)


class SliceParam(Params):
    begin = field(tuple_of(int), required=True)
    end = field(tuple_of(int), required=True)


def _slice_shape(p, in_shapes):
    out = tuple(e - b for b, e in zip(p.begin, p.end))
    return in_shapes, out


register_simple_op(
    "slice", lambda p, x: x[tuple(slice(b, e) for b, e in zip(p.begin, p.end))],
    nin=1, param_cls=SliceParam, shape_rule=_slice_shape, aliases=("crop_like",))


def _check_crop_region(begin, end, shape, opname):
    if not (len(begin) == len(end) == len(shape)):
        raise ValueError(f"{opname}: begin/end ndim must match data ndim")
    for b, e, d in zip(begin, end, shape):
        if not (0 <= b <= e <= d):
            raise ValueError(
                f"{opname}: region [{begin}, {end}) out of bounds for {shape}")


def _crop_assign_shape(p, in_shapes):
    lhs, rhs = in_shapes
    if lhs is None:
        raise ValueError("_crop_assign: lhs shape unknown")
    _check_crop_region(p.begin, p.end, lhs, "_crop_assign")
    want = tuple(e - b for b, e in zip(p.begin, p.end))
    if rhs is not None and tuple(rhs) != want:
        raise ValueError(f"_crop_assign: rhs shape {rhs} != region {want}")
    return [lhs, want], tuple(lhs)


def _crop_assign(p, lhs, rhs):
    # Functional form of the reference's inplace region write
    # (matrix_op-inl.h:453 CropAssign, kWriteInplace): returns lhs with
    # [begin, end) overwritten by rhs.  Shapes are static under jit, so
    # bounds-check eagerly — dynamic_update_slice would silently clamp.
    _check_crop_region(p.begin, p.end, lhs.shape, "_crop_assign")
    want = tuple(e - b for b, e in zip(p.begin, p.end))
    if tuple(rhs.shape) != want:
        raise ValueError(
            f"_crop_assign: rhs shape {tuple(rhs.shape)} != region {want}")
    return jax.lax.dynamic_update_slice(lhs, rhs.astype(lhs.dtype), p.begin)


register_simple_op("_crop_assign", _crop_assign, nin=2,
                   param_cls=SliceParam, shape_rule=_crop_assign_shape,
                   aliases=("_slice_assign",))


class CropAssignScalarParam(Params):
    begin = field(tuple_of(int), required=True)
    end = field(tuple_of(int), required=True)
    scalar = field(float, default=0.0, doc="value written into the region")


def _crop_assign_scalar(p, x):
    # matrix_op-inl.h:535 CropAssignScalar.  Eager bounds check as in
    # _crop_assign: dynamic_update_slice silently clamps out-of-bounds.
    _check_crop_region(p.begin, p.end, x.shape, "_crop_assign_scalar")
    region = tuple(e - b for b, e in zip(p.begin, p.end))
    fill = jnp.full(region, p.scalar, dtype=x.dtype)
    return jax.lax.dynamic_update_slice(x, fill, p.begin)


def _crop_assign_scalar_shape(p, in_shapes):
    if in_shapes[0] is None:
        raise ValueError("_crop_assign_scalar: input shape unknown")
    _check_crop_region(p.begin, p.end, in_shapes[0], "_crop_assign_scalar")
    return in_shapes, tuple(in_shapes[0])


register_simple_op("_crop_assign_scalar", _crop_assign_scalar, nin=1,
                   param_cls=CropAssignScalarParam,
                   shape_rule=_crop_assign_scalar_shape,
                   aliases=("_slice_assign_scalar",))


# -- Reshape / Flatten -------------------------------------------------------
class ReshapeParam(Params):
    shape = field(tuple_of(int), default=None,
                  doc="target shape; 0 copies input dim, -1 infers one dim, "
                      "-2 copies all remaining dims, -3 merges two "
                      "consecutive dims, -4 splits one dim into the next "
                      "two spec entries")
    reverse = field(bool, default=False,
                    doc="match the special codes from the right")
    target_shape = field(tuple_of(int), default=None,
                         doc="legacy alias; 0 infers the remainder")
    keep_highest = field(bool, default=False,
                         doc="legacy: ignore target_shape[0] and keep the "
                             "input's first dim unchanged")


def _apply_reshape_codes(src, spec):
    """Reference InferReshapeShape (reshape-inl.h): resolve the 0/-1/-2/
    -3/-4 codes of ``spec`` against input shape ``src``."""
    out = []
    i = 0  # cursor into src; advanced by the consuming codes
    j = 0
    infer_at = None
    while j < len(spec):
        d = spec[j]
        if d in (0, -3, -4) and i >= len(src):
            raise ValueError(
                f"Reshape: code {d} at position {j} consumes input dim "
                f"{i}, but the input has only {len(src)} dims")
        if d == -3 and i + 1 >= len(src):
            raise ValueError(
                f"Reshape: -3 at position {j} merges input dims {i} and "
                f"{i + 1}, but the input has only {len(src)} dims")
        if d == 0:
            out.append(src[i])
            i += 1
        elif d == -1:
            if infer_at is not None:
                raise ValueError("Reshape: at most one -1 allowed")
            infer_at = len(out)
            out.append(1)
            i += 1
        elif d == -2:
            out.extend(src[i:])
            i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1])
            i += 2
        elif d == -4:
            if j + 2 >= len(spec):
                raise ValueError(
                    "Reshape: -4 needs two following entries in the spec")
            d1, d2 = spec[j + 1], spec[j + 2]
            if (d1 == -1 and d2 == -1) or d1 == 0 or d2 == 0 \
                    or d1 < -1 or d2 < -1:
                raise ValueError(
                    f"Reshape: -4 operands must be positive with at most "
                    f"one -1, got ({d1}, {d2})")
            whole = src[i]
            if d1 == -1:
                d1 = whole // d2
            if d2 == -1:
                d2 = whole // d1
            if d1 * d2 != whole:
                raise ValueError(
                    f"Reshape: -4 cannot split {whole} into ({spec[j+1]}, "
                    f"{spec[j+2]})")
            out.extend([d1, d2])
            i += 1
            j += 2
        else:
            out.append(d)
            i += 1
        j += 1
    return out, infer_at


def _resolve_reshape(p, in_shape):
    in_shape = tuple(in_shape)
    total = int(np.prod(in_shape)) if in_shape else 1
    if p.shape is not None:
        spec = list(p.shape)
        if p.reverse:
            out, infer_at = _apply_reshape_codes(in_shape[::-1], spec[::-1])
            out = out[::-1]
            if infer_at is not None:
                infer_at = len(out) - 1 - infer_at
        else:
            out, infer_at = _apply_reshape_codes(in_shape, spec)
    elif p.target_shape is not None:
        # legacy API: 0 infers the remaining elements
        out = list(p.target_shape)
        if p.keep_highest:
            out[0] = in_shape[0]
        infer_at = out.index(0) if 0 in out else None
        if infer_at is not None:
            out[infer_at] = 1
    else:
        raise ValueError("Reshape: no target shape")
    spec_desc = p.shape if p.shape is not None else p.target_shape
    if infer_at is not None:
        known = int(np.prod(out)) or 1
        if total % known:
            raise ValueError(f"Reshape: cannot infer dim reshaping "
                             f"{in_shape} with {tuple(spec_desc)}")
        out[infer_at] = total // known
    if int(np.prod(out) if out else 1) != total:
        raise ValueError(f"Reshape: cannot reshape {in_shape} to "
                         f"{tuple(spec_desc)}")
    return tuple(out)


register_simple_op(
    "Reshape", lambda p, x: jnp.reshape(x, _resolve_reshape(p, x.shape)), nin=1,
    param_cls=ReshapeParam,
    shape_rule=lambda p, s: (s, _resolve_reshape(p, s[0])), aliases=("reshape",))

register_simple_op(
    "Flatten", lambda x: jnp.reshape(x, (x.shape[0], -1)), nin=1,
    shape_rule=lambda p, s: (s, (s[0][0], int(np.prod(s[0][1:])) if len(s[0]) > 1 else 1)),
    aliases=("flatten",))


# -- Cast --------------------------------------------------------------------
class CastParam(Params):
    dtype = field(str, required=True, doc="target dtype name")


def _cast_dtype(p, in_dtypes):
    ins = [d if d is not None else np.dtype(np.float32) for d in in_dtypes]
    return ins, [np_dtype(p.dtype)], []


register_simple_op("Cast", lambda p, x: x.astype(np_dtype(p.dtype)), nin=1,
                   param_cls=CastParam, dtype_rule=_cast_dtype, aliases=("cast",))


# -- Concat / SliceChannel (multi-arity full ops) ----------------------------
class ConcatParam(Params):
    num_args = field(int, required=True, lower=1)
    dim = field(int, default=1, doc="axis to concatenate on")


@register_op("Concat", aliases=("concat",))
class ConcatOp(OpDef):
    key_var_num_args = "num_args"
    param_cls = ConcatParam

    def list_arguments(self, params):
        return [f"arg{i}" for i in range(params.num_args)]

    def infer_shape(self, params, in_shapes):
        known = [s for s in in_shapes if s is not None]
        if not known:
            raise ValueError("Concat: no input shape known")
        ref = list(known[0])
        dim = params.dim % len(ref)
        total = 0
        for s in in_shapes:
            if s is None:
                raise ValueError("Concat: all input shapes required")
            total += s[dim]
        ref[dim] = total
        return list(in_shapes), [tuple(ref)], []

    def forward(self, params, inputs, aux, train, key):
        return [jnp.concatenate(inputs, axis=params.dim)], []


class SliceChannelParam(Params):
    num_outputs = field(int, required=True, lower=1)
    axis = field(int, default=1)
    squeeze_axis = field(bool, default=False)


@register_op("SliceChannel", aliases=("slice_channel", "split"))
class SliceChannelOp(OpDef):
    param_cls = SliceChannelParam

    def list_outputs(self, params):
        return [f"output{i}" for i in range(params.num_outputs)]

    def infer_shape(self, params, in_shapes):
        s = list(in_shapes[0])
        ax = params.axis % len(s)
        if s[ax] % params.num_outputs:
            raise ValueError(f"SliceChannel: dim {s[ax]} not divisible by "
                             f"{params.num_outputs}")
        s[ax] //= params.num_outputs
        if params.squeeze_axis and s[ax] == 1:
            out = tuple(d for i, d in enumerate(s) if i != ax)
        else:
            out = tuple(s)
        return list(in_shapes), [out] * params.num_outputs, []

    def forward(self, params, inputs, aux, train, key):
        parts = jnp.split(inputs[0], params.num_outputs, axis=params.axis)
        if params.squeeze_axis:
            parts = [jnp.squeeze(p, axis=params.axis) for p in parts]
        return list(parts), []


# -- Pad ---------------------------------------------------------------------
class PadParam(Params):
    mode = field(str, default="constant", enum=("constant", "edge", "reflect"))
    pad_width = field(tuple_of(int), required=True,
                      doc="(before, after) per axis, flattened; NCHW 4D uses 8 ints")
    constant_value = field(float, default=0.0)


def _pad_shape(p, in_shapes):
    s = in_shapes[0]
    pw = p.pad_width
    out = tuple(d + pw[2 * i] + pw[2 * i + 1] for i, d in enumerate(s))
    return in_shapes, out


def _pad(p, x):
    pw = [(p.pad_width[2 * i], p.pad_width[2 * i + 1]) for i in range(x.ndim)]
    if p.mode == "constant":
        return jnp.pad(x, pw, constant_values=p.constant_value)
    return jnp.pad(x, pw, mode=p.mode)


register_simple_op("Pad", _pad, nin=1, param_cls=PadParam, shape_rule=_pad_shape,
                   aliases=("pad",))


# -- Crop (spatial center/offset crop, src/operator/crop-inl.h) --------------
class CropParam(Params):
    num_args = field(int, default=1)
    offset = field(tuple_of(int), default=(0, 0))
    h_w = field(tuple_of(int), default=(0, 0))
    center_crop = field(bool, default=False)


@register_op("Crop")
class CropOp(OpDef):
    key_var_num_args = "num_args"
    param_cls = CropParam

    def list_arguments(self, params):
        return ["data"] if params.num_args == 1 else ["data", "crop_like"]

    def _target_hw(self, params, in_shapes):
        if params.num_args == 2:
            return in_shapes[1][2], in_shapes[1][3]
        return params.h_w

    def infer_shape(self, params, in_shapes):
        n, c = in_shapes[0][0], in_shapes[0][1]
        h, w = self._target_hw(params, in_shapes)
        return list(in_shapes), [(n, c, h, w)], []

    def forward(self, params, inputs, aux, train, key):
        x = inputs[0]
        if params.num_args == 2:
            th, tw = inputs[1].shape[2], inputs[1].shape[3]
        else:
            th, tw = params.h_w
        if params.center_crop:
            oh = (x.shape[2] - th) // 2
            ow = (x.shape[3] - tw) // 2
        else:
            oh, ow = params.offset
        return [x[:, :, oh:oh + th, ow:ow + tw]], []


# -- tile / repeat (convenience parity) --------------------------------------
class TileParam(Params):
    reps = field(tuple_of(int), required=True)


register_simple_op(
    "tile", lambda p, x: jnp.tile(x, p.reps), nin=1, param_cls=TileParam,
    shape_rule=lambda p, s: (s, tuple(np.tile(np.empty(s[0], dtype=np.int8), p.reps).shape)))


class OneHotParam(Params):
    depth = field(int, required=True)
    on_value = field(float, default=1.0)
    off_value = field(float, default=0.0)


register_simple_op(
    "one_hot",
    lambda p, x: jnp.where(
        (jnp.arange(p.depth) == x.astype(jnp.int32)[..., None]), p.on_value, p.off_value
    ).astype(jnp.float32),
    nin=1, param_cls=OneHotParam,
    shape_rule=lambda p, s: (s, tuple(s[0]) + (p.depth,)))
