"""Operator layer: registry + op families.

Importing this package registers every operator (the reference's
equivalent of linking src/operator/*.cc registrations into libmxnet).
"""

from .op import OP_REGISTRY, OpDef, SimpleOpDef, register_op, register_simple_op

# Register op families (import order irrelevant; each module self-registers).
from . import elementwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import sequence  # noqa: F401
from . import rnn  # noqa: F401
from . import vision  # noqa: F401
from . import quantized  # noqa: F401
from . import multibox  # noqa: F401
from . import sample  # noqa: F401
from . import attention  # noqa: F401

from .attention import paged_attention
from .flash_attention import flash_attention

__all__ = ["OP_REGISTRY", "OpDef", "SimpleOpDef", "register_op",
           "register_simple_op", "flash_attention", "paged_attention"]
