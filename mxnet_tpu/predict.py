"""Predict-only API + standalone deploy artifacts.

TPU-native rebuild of the reference's predict mini-API and amalgamation
deploy story:

- ``Predictor`` mirrors the C predict API surface
  (include/mxnet/c_predict_api.h, src/c_api/c_predict_api.cc:1-305):
  create from symbol JSON + a param blob, set named inputs, ``forward``,
  ``partial_forward``, fetch output shapes/values, ``reshape`` to new
  input shapes.  Where the reference forces the Naive engine under
  ``MXNET_PREDICT_ONLY`` (base.h:68, engine.cc:28-30), here inference is
  a single fused, donation-friendly XLA program — there is no scheduler
  to strip out.
- ``export_model`` / ``ExportedPredictor`` replace amalgamation
  (amalgamation/: one-file predict-only build for mobile/JS): the
  deployable artifact is a serialized StableHLO executable
  (``jax.export``) plus the param tree.  Loading it needs only jax —
  none of the Symbol/graph machinery — which is the XLA-era equivalent
  of compiling the mini predict runtime into one object.
"""

from __future__ import annotations

import io
import json
import os
import zipfile

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError, np_dtype
from .context import current_context

__all__ = ["Predictor", "create", "export_model", "load_exported",
           "ExportedPredictor"]


def _split_params(param_dict):
    """Split an ``arg:``/``aux:`` prefixed blob (model.save_checkpoint
    naming, reference model.py:318-347) into (arg_params, aux_params)."""
    arg_params, aux_params = {}, {}
    for k, v in param_dict.items():
        if k.startswith("arg:"):
            arg_params[k[4:]] = v
        elif k.startswith("aux:"):
            aux_params[k[4:]] = v
        else:  # unprefixed blobs are treated as args (c_predict_api.cc:88-104)
            arg_params[k] = v
    return arg_params, aux_params


class Predictor:
    """Inference-only executor (reference ``MXPredCreate`` family).

    Parameters
    ----------
    symbol_json : str
        Symbol JSON string (or a path to one).
    params : dict | str | bytes
        ``arg:``/``aux:``-prefixed param dict, or the path of a
        ``.params`` blob saved by ``save_checkpoint``.
    input_shapes : dict(name -> shape)
        Shapes for the data inputs; remaining shapes are inferred
        (partial-shape support, c_predict_api.h MXPredCreatePartialOut).
    ctx : Context, optional
    dtype : optional
        Cast parameters to this dtype (e.g. ``"bfloat16"`` for MXU-
        friendly serving).
    """

    def __init__(self, symbol_json, params, input_shapes, ctx=None, dtype=None):
        ctx = ctx or current_context()
        if os.path.exists(symbol_json):
            with open(symbol_json) as f:
                symbol_json = f.read()
        self.symbol = sym_mod.load_json(symbol_json)
        if isinstance(params, (str, os.PathLike)):
            params = nd.load(params)
        elif isinstance(params, bytes):
            params = nd.load(io.BytesIO(params))
        arg_params, aux_params = _split_params(params)
        self._arg_params = {k: (v if isinstance(v, nd.NDArray)
                                else nd.array(v, ctx=ctx)) for k, v in arg_params.items()}
        self._aux_params = {k: (v if isinstance(v, nd.NDArray)
                                else nd.array(v, ctx=ctx)) for k, v in aux_params.items()}
        if dtype is not None:
            dt = np_dtype(dtype)
            self._arg_params = {k: v.astype(dt) for k, v in self._arg_params.items()}
        self._ctx = ctx
        self._dtype = dtype
        self.output_names = self.symbol.list_outputs()
        self._bind(dict(input_shapes))

    def _bind(self, input_shapes):
        self._input_shapes = dict(input_shapes)
        arg_names = self.symbol.list_arguments()
        free_names = [n for n in arg_names if n not in self._arg_params]
        # like MXPredCreate, only data inputs need shapes; other free
        # variables (e.g. output-layer labels) are inferred and zero-filled
        # (c_predict_api.cc partial-shape handling)
        self._data_names = [n for n in free_names if n in input_shapes]
        if not self._data_names:
            raise MXNetError(
                f"input_shapes must cover at least one data input "
                f"(free inputs: {free_names})")
        arg_shapes, _, aux_shapes = self.symbol.infer_shape_partial(
            **input_shapes)
        unknown = [n for n, s in zip(arg_names, arg_shapes)
                   if n in free_names and n not in input_shapes
                   and (s is None or any(d == 0 for d in s))]
        if unknown:
            raise MXNetError(
                f"input_shapes missing entries for data inputs {unknown}")
        args = {}
        for name, shape in zip(arg_names, arg_shapes):
            if name in self._arg_params:
                p = self._arg_params[name]
                if tuple(p.shape) != tuple(shape):
                    raise MXNetError(
                        f"param {name!r} shape {p.shape} != inferred {shape}")
                args[name] = p
            else:
                dt = np_dtype(self._dtype) if self._dtype else np.float32
                args[name] = nd.zeros(shape, ctx=self._ctx, dtype=dt)
        aux = {}
        for name, shape in zip(self.symbol.list_auxiliary_states(), aux_shapes):
            if name in self._aux_params:
                aux[name] = self._aux_params[name]
            else:
                aux[name] = nd.zeros(shape, ctx=self._ctx)
        self._exec = self.symbol.bind(self._ctx, args, aux_states=aux,
                                      grad_req="null")
        self._internals_exec = None
        self._partial_step = 0

    # -- C predict API surface ----------------------------------------------
    def set_input(self, name, value):
        """``MXPredSetInput``: copy a named input into the bound array."""
        if name not in self._data_names:
            raise MXNetError(f"{name!r} is not a data input "
                             f"(inputs: {self._data_names})")
        self._exec.arg_dict[name][:] = value

    def forward(self, **kwargs):
        """``MXPredForward``; kwargs set inputs first."""
        for k, v in kwargs.items():
            self.set_input(k, v)
        self._exec.forward(is_train=False)
        self._partial_step = 0
        return self._exec.outputs

    def partial_forward(self, step):
        """``MXPredPartialForward``: run through internal head ``step``.

        Returns the number of remaining steps (0 when the whole graph has
        run).  Internal outputs become available via ``get_internal``.
        """
        if self._internals_exec is None:
            internals = self.symbol.get_internals()
            arg_names = internals.list_arguments()
            args = {}
            for name in arg_names:
                if name in self._arg_params:
                    args[name] = self._arg_params[name]
                else:
                    args[name] = self._exec.arg_dict[name]
            aux = {name: self._aux_params.get(
                name, self._exec.aux_dict.get(name))
                for name in internals.list_auxiliary_states()}
            self._internals = internals
            self._internals_exec = internals.bind(
                self._ctx, args, aux_states=aux, grad_req="null")
        n = len(self._internals.list_outputs())
        if not 0 <= step < n:
            raise MXNetError(f"step {step} out of range [0, {n})")
        self._internals_exec.forward(is_train=False)
        self._partial_step = step
        return n - step - 1

    def get_internal(self, step=None):
        """Output of internal head ``step`` after ``partial_forward``."""
        if self._internals_exec is None:
            raise MXNetError("call partial_forward first")
        step = self._partial_step if step is None else step
        return self._internals_exec.outputs[step]

    def get_output_shape(self, index=0):
        """``MXPredGetOutputShape`` without running forward."""
        _, out_shapes, _ = self.symbol.infer_shape(**self._input_shapes)
        return tuple(out_shapes[index])

    def get_output(self, index=0):
        """``MXPredGetOutput``: copy output ``index`` to host numpy."""
        return self._exec.outputs[index].asnumpy()

    # -- flat-buffer accessors for the C predict API (src/predict_capi.cc)
    def set_input_flat(self, name, values):
        """Set input ``name`` from raw float32 bytes (or any flat float
        sequence) — the zero-boxing C ABI path."""
        shape = self._input_shapes[name]
        if isinstance(values, (bytes, bytearray, memoryview)):
            arr = np.frombuffer(values, dtype=np.float32).reshape(shape)
        else:
            arr = np.asarray(values, dtype=np.float32).reshape(shape)
        self.set_input(name, arr)

    def get_output_flat(self, index=0):
        """Output ``index`` as raw float32 bytes (C ABI path)."""
        return np.ascontiguousarray(
            self.get_output(index), dtype=np.float32).tobytes()

    def reshape(self, input_shapes):
        """``MXPredReshape``: rebind with new input shapes (weights kept)."""
        self._bind(dict(input_shapes))

    @property
    def data_names(self):
        return list(self._data_names)

    # -- deploy -------------------------------------------------------------
    def export(self, path, platforms=None):
        """Serialize this predictor into a standalone artifact (see
        ``export_model``)."""
        export_model(path, self.symbol, self._arg_params, self._aux_params,
                     self._input_shapes, dtype=self._dtype,
                     platforms=platforms)


def create(symbol_json, params, input_shapes, ctx=None, **kwargs):
    """``MXPredCreate`` analog."""
    return Predictor(symbol_json, params, input_shapes, ctx=ctx, **kwargs)


# ---------------------------------------------------------------------------
# Standalone deploy artifact (amalgamation analog)
# ---------------------------------------------------------------------------

_MANIFEST = "manifest.json"
_STABLEHLO = "model.stablehlo"
_PARAMS = "params.npz"
_SYMBOL = "symbol.json"


def export_model(path, symbol, arg_params, aux_params, input_shapes,
                 dtype=None, platforms=None):
    """Export (symbol, params) as one self-contained inference artifact.

    The artifact is a zip holding serialized StableHLO (``jax.export``)
    of the fused inference program, the flattened parameters, and a
    manifest — loadable with only jax + numpy (``load_exported``).  This
    is the TPU-era replacement for the amalgamation predict-only build
    (reference amalgamation/README; c_predict_api consumed by it).

    ``platforms`` (e.g. ``["cpu", "tpu"]``) lowers the artifact for
    several backends — the cross-compile analog of amalgamation's
    mobile targets.  Default: the current default jax backend only.
    Note the backends' numerics differ slightly (TPU matmuls default to
    bf16-accumulated passes), so outputs match per-platform, not across.
    """
    import jax

    from .executor import _CompiledGraph
    from .jax_compat import export_fn

    graph = _CompiledGraph(symbol)
    arg_names = symbol.list_arguments()
    aux_names = symbol.list_auxiliary_states()
    free_names = [n for n in arg_names if n not in arg_params]
    data_names = [n for n in free_names if n in input_shapes]
    arg_shapes, _, aux_shapes = symbol.infer_shape_partial(**input_shapes)
    shape_of = dict(zip(arg_names, arg_shapes))

    def as_np(v):
        return v.asnumpy() if isinstance(v, nd.NDArray) else np.asarray(v)

    params_np = {f"arg:{k}": as_np(v) for k, v in arg_params.items()}
    # non-data free inputs (labels) are baked in as zeros — unused at eval
    for n in free_names:
        if n not in data_names:
            params_np[f"arg:{n}"] = np.zeros(tuple(shape_of[n]), np.float32)
    params_np.update({f"aux:{k}": as_np(v) for k, v in aux_params.items()})
    if dtype is not None:
        dt = np_dtype(dtype)
        params_np = {k: (v.astype(dt) if k.startswith("arg:") else v)
                     for k, v in params_np.items()}

    def infer_fn(data, params):
        key = jax.random.PRNGKey(0)
        args = {k: params[f"arg:{k}"] for k in arg_names if k not in data_names}
        args.update(data)
        aux = {k: params[f"aux:{k}"] for k in aux_names}
        outs, _ = graph(args, aux, key, False)
        return outs

    data_dt = np_dtype(dtype) if dtype else np.float32
    data_spec = {n: jax.ShapeDtypeStruct(tuple(shape_of[n]), data_dt)
                 for n in data_names}
    param_spec = {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                  for k, v in params_np.items()}
    kw = {"platforms": list(platforms)} if platforms else {}
    exported = export_fn(jax.jit(infer_fn), data_spec, param_spec, **kw)
    manifest = {
        "format": "mxnet_tpu.exported_model.v1",
        "data_names": data_names,
        "input_shapes": {n: list(shape_of[n]) for n in data_names},
        "output_names": symbol.list_outputs(),
        "dtype": str(np.dtype(data_dt)),
    }
    from .ndarray import _encode_bf16

    buf = io.BytesIO()
    np.savez(buf, **_encode_bf16(params_np))
    # entries deliberately STORED (no deflate): the amalgamation C
    # runtime (amalgamation/mxtpu_predict.c) parses the zip + npz with
    # no zlib — one artifact serves both the jax loader and the
    # Python-free deploy target
    with zipfile.ZipFile(path, "w", compression=zipfile.ZIP_STORED) as zf:
        zf.writestr(_MANIFEST, json.dumps(manifest, indent=1))
        zf.writestr(_STABLEHLO, exported.serialize())
        zf.writestr(_PARAMS, buf.getvalue())
        zf.writestr(_SYMBOL, symbol.tojson())


class ExportedPredictor:
    """Runs an ``export_model`` artifact.  Needs only jax/numpy at load
    time — the graph is already compiled to StableHLO."""

    def __init__(self, path):
        from .jax_compat import deserialize_exported

        with zipfile.ZipFile(path) as zf:
            self.manifest = json.loads(zf.read(_MANIFEST))
            self._exported = deserialize_exported(zf.read(_STABLEHLO))
            from .ndarray import _decode_bf16

            with np.load(io.BytesIO(zf.read(_PARAMS))) as pz:
                self._params = _decode_bf16({k: pz[k] for k in pz.files})
        self.data_names = self.manifest["data_names"]
        self.output_names = self.manifest["output_names"]
        self._inputs = {}

    def set_input(self, name, value):
        if name not in self.data_names:
            raise MXNetError(f"{name!r} not an input ({self.data_names})")
        dt = np.dtype(self.manifest["dtype"]) if self.manifest["dtype"] != "bfloat16" \
            else np_dtype("bfloat16")
        self._inputs[name] = np.asarray(value, dtype=dt)

    def forward(self, **kwargs):
        for k, v in kwargs.items():
            self.set_input(k, v)
        missing = [n for n in self.data_names if n not in self._inputs]
        if missing:
            raise MXNetError(f"inputs not set: {missing}")
        self._outputs = self._exported.call(
            {n: self._inputs[n] for n in self.data_names}, self._params)
        return self._outputs

    def get_output(self, index=0):
        return np.asarray(self._outputs[index])


def load_exported(path):
    return ExportedPredictor(path)
