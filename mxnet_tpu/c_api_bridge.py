"""Python side of the flat C training ABI.

The reference exposes its full training surface through ~109 C entry
points (``/root/reference/include/mxnet/c_api.h``): NDArray CRUD +
imperative invoke (``src/c_api/c_api.cc:410-436``), Symbol
create/compose/infer (``c_api.cc:758+``), Executor
bind/forward/backward (``c_api.cc:956-1110``), DataIter
(``c_api.cc:1153``) and KVStore (``c_api.h:1227+``).  Every non-Python
frontend (R, Scala, Matlab, the C++ amalgamation) is a thin veneer over
that ABI.

In this framework the runtime *is* the Python/JAX layer, so the native
``src/train_capi.cc`` bridges each C entry point to one plain function
here (through the embedded/attached CPython interpreter, the same
mechanism as ``src/predict_capi.cc``).  Functions in this module
deliberately take and return only simple types — str/int/bytes/lists
and opaque objects the C side holds as handles — so the C++ glue stays
mechanical.

All kwargs arriving from C are strings (the reference's C API has the
same convention — dmlc::Parameter parses strings); ``_parse`` applies
``ast.literal_eval`` with a string fallback so ``"(3,3)"``, ``"32"``,
``"True"`` and ``"relu"`` all coerce correctly.
"""

from __future__ import annotations

import ast

import numpy as np

__all__ = []  # C-ABI internal; not a user-facing module


# int dtype codes across the ABI — the reference's mshadow TypeFlag order
# (include/mxnet/base.h): 0=f32 1=f64 2=f16 3=u8 4=i32 5=i8 6=i64; we add
# 7=bf16 (TPU-native) and 8=bool.
_DTYPES = ["float32", "float64", "float16", "uint8", "int32", "int8",
           "int64", "bfloat16", "bool"]


def _np_dtype(code):
    name = _DTYPES[code]
    if name == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


def _dtype_code(dtype):
    name = np.dtype(dtype).name
    if name not in _DTYPES:
        raise ValueError(f"no ABI dtype code for {name}")
    return _DTYPES.index(name)


def _parse(s):
    """String→python value for C-ABI kwargs (dmlc::Parameter analog)."""
    if not isinstance(s, str):
        return s
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _parse_kwargs(keys, vals):
    return {k: _parse(v) for k, v in zip(keys, vals)}


def _ctx(dev_type, dev_id):
    from . import context
    return {1: context.cpu, 2: context.gpu, 3: context.cpu_pinned,
            4: context.tpu}.get(dev_type, context.cpu)(dev_id)


# -- NDArray (MXNDArrayCreate* / SyncCopy* / WaitAll analogs) ---------------

def nd_create(shape, dtype_code, dev_type, dev_id):
    from .ndarray import NDArray
    return NDArray(np.zeros(tuple(shape), dtype=_np_dtype(dtype_code)),
                   ctx=_ctx(dev_type, dev_id))


def nd_from_bytes(nd, data):
    """SyncCopyFromCPU: raw little-endian bytes -> device array."""
    arr = np.frombuffer(data, dtype=np.dtype(nd.dtype)).reshape(nd.shape)
    nd[:] = arr
    return True


def nd_to_bytes(nd):
    """SyncCopyToCPU: device array -> raw bytes (blocks until ready)."""
    return np.ascontiguousarray(nd.asnumpy()).tobytes()


def nd_shape(nd):
    return tuple(int(d) for d in nd.shape)


def nd_dtype(nd):
    return _dtype_code(nd.dtype)


def nd_wait_all():
    from . import ndarray
    ndarray.waitall()
    return True


def nd_save(fname, names, arrays):
    from . import ndarray
    if names:
        ndarray.save(fname, dict(zip(names, arrays)))
    else:
        ndarray.save(fname, list(arrays))
    return True


def nd_load(fname):
    from . import ndarray
    data = ndarray.load(fname)
    if isinstance(data, dict):
        names, arrays = list(data.keys()), list(data.values())
    else:
        names, arrays = [], list(data)
    return names, arrays


def func_invoke(op_name, inputs, keys, vals):
    """Imperative op invoke on NDArrays (MXFuncInvoke / MXImperativeInvoke
    analog, reference c_api.cc:410-436): look the op up in the runtime
    registry and apply it through the NDArray function surface."""
    from . import ndarray as nd_mod
    fn = getattr(nd_mod, op_name, None)
    if fn is None:
        from . import nd as nd_ns
        fn = getattr(nd_ns, op_name, None)
    if fn is None:
        raise KeyError(f"no NDArray function {op_name!r}")
    out = fn(*inputs, **_parse_kwargs(keys, vals))
    if isinstance(out, (list, tuple)):
        return list(out)
    return [out]


# -- Symbol (MXSymbolCreate* / Compose / Infer analogs) ---------------------

class AtomicSymbol:
    """A created-but-uncomposed op, the reference's AtomicSymbolCreator
    product: MXSymbolCreateAtomicSymbol returns one of these; Compose
    turns it into a real graph node."""

    def __init__(self, op_name, kwargs):
        self.op_name = op_name
        self.kwargs = kwargs


def symbol_create_variable(name):
    from . import symbol
    return symbol.Variable(name)


def symbol_create_atomic(op_name, keys, vals):
    return AtomicSymbol(op_name, _parse_kwargs(keys, vals))


def symbol_compose(handle, name, keys, args):
    """Compose an atomic symbol with inputs → full Symbol.  ``keys`` may
    be None (positional) or parallel to ``args`` (named inputs)."""
    from . import symbol
    if not isinstance(handle, AtomicSymbol):
        raise TypeError("compose target must be an uncomposed atomic symbol")
    kwargs = dict(handle.kwargs)
    if name:
        kwargs["name"] = name
    if keys:
        kwargs.update(dict(zip(keys, args)))
        return symbol._create(handle.op_name, [], kwargs)
    return symbol._create(handle.op_name, list(args), kwargs)


def symbol_from_json(json_str):
    from . import symbol
    return symbol.load_json(json_str)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_list_arguments(sym):
    return list(sym.list_arguments())


def symbol_list_outputs(sym):
    return list(sym.list_outputs())


def symbol_list_aux(sym):
    return list(sym.list_auxiliary_states())


def symbol_copy(sym):
    """Deep graph copy (MXSymbolCopy semantics): the copy's nodes must not
    share attrs with the original, so round-trip through graph JSON."""
    from . import symbol
    return symbol.load_json(sym.tojson())


def symbol_get_internals(sym):
    return sym.get_internals()


def symbol_get_output(sym, index):
    return sym[int(index)]


def symbol_get_attr(sym, key):
    v = sym.attr(key)
    return "" if v is None else str(v)


def symbol_set_attr(sym, key, value):
    sym._set_attr(**{key: value})
    return True


def symbol_infer_shape(sym, keys, shapes, partial):
    """Returns (complete, arg_shapes, out_shapes, aux_shapes); shape lists
    are tuples (empty tuple for unknown when partial)."""
    kwargs = {k: tuple(s) for k, s in zip(keys, shapes)}
    fn = sym.infer_shape_partial if partial else sym.infer_shape
    arg_shapes, out_shapes, aux_shapes = fn(**kwargs)
    if arg_shapes is None:
        return False, [], [], []
    # partial mode reports unknown shapes as None entries; the C contract
    # is *complete == 0 whenever inference is underdetermined
    complete = not any(
        s is None
        for s in list(arg_shapes) + list(out_shapes) + list(aux_shapes))
    clean = lambda lst: [tuple(int(d) for d in (s or ())) for s in lst]
    return complete, clean(arg_shapes), clean(out_shapes), clean(aux_shapes)


# -- Executor (MXExecutorBind/Forward/Backward/Outputs analogs) -------------

_GRAD_REQ = {0: "null", 1: "write", 2: "add"}


def executor_bind(sym, dev_type, dev_id, args, arg_grads, reqs, auxs):
    ctx = _ctx(dev_type, dev_id)
    grads = list(arg_grads)
    req = [_GRAD_REQ[int(r)] for r in reqs]
    return sym.bind(ctx, list(args), args_grad=grads, grad_req=req,
                    aux_states=list(auxs) if auxs else None)


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))
    return True


def executor_backward(ex, head_grads):
    ex.backward(list(head_grads) if head_grads else None)
    return True


def executor_outputs(ex):
    return list(ex.outputs)


# -- KVStore (MXKVStore* analogs) -------------------------------------------

def kvstore_create(kind):
    from . import kvstore
    return kvstore.create(kind)


def kvstore_init(kv, keys, vals):
    for k, v in zip(keys, vals):
        kv.init(int(k), v)
    return True


def kvstore_push(kv, keys, vals, priority):
    kv.push([int(k) for k in keys], list(vals), priority=priority)
    return True


def kvstore_pull(kv, keys, outs, priority):
    kv.pull([int(k) for k in keys], out=list(outs), priority=priority)
    return True


def kvstore_set_optimizer(kv, name, keys, vals):
    from .optimizer import Optimizer
    opt = Optimizer.create_optimizer(name, **_parse_kwargs(keys, vals))
    kv.set_optimizer(opt)
    return True


def kvstore_rank(kv):
    return int(kv.rank)


def kvstore_num_workers(kv):
    return int(kv.num_workers)


def kvstore_type(kv):
    return str(kv.type)


def kvstore_barrier(kv):
    kv.barrier()
    return True


# -- DataIter (MXDataIterCreate*/Next/GetData analogs) ----------------------

def _iter_registry():
    from . import io
    return io.iter_registry()


def list_data_iters():
    return sorted(_iter_registry())


class _IterAdapter:
    """One-batch lookahead adapter: C's MXDataIterNext contract is
    next()->bool then GetData/GetLabel/GetPad on the current batch."""

    def __init__(self, it):
        self.it = it
        self.batch = None

    def next(self):
        try:
            self.batch = self.it.next()
            return True
        except StopIteration:
            self.batch = None
            return False

    def before_first(self):
        self.it.reset()
        self.batch = None
        return True

    def data(self):
        return self.batch.data[0]

    def label(self):
        return self.batch.label[0]

    def pad(self):
        return int(self.batch.pad or 0)


def dataiter_create(name, keys, vals):
    cls = _iter_registry().get(name)
    if cls is None:
        raise KeyError(f"no data iterator {name!r}; have {list_data_iters()}")
    return _IterAdapter(cls(**_parse_kwargs(keys, vals)))


def dataiter_next(h):
    return h.next()


def dataiter_before_first(h):
    return h.before_first()


def dataiter_data(h):
    return h.data()


def dataiter_label(h):
    return h.label()


def dataiter_pad(h):
    return h.pad()


# -- misc -------------------------------------------------------------------

def random_seed(seed):
    from . import random as rnd
    rnd.seed(int(seed))
    return True


# -- extended NDArray surface ----------------------------------------------

def nd_slice(nd, begin, end):
    """MXNDArraySlice: contiguous [begin, end) view along axis 0."""
    return nd[int(begin):int(end)]


def nd_at(nd, idx):
    """MXNDArrayAt: index along axis 0 (drops the axis)."""
    return nd[int(idx)]


def nd_reshape(nd, shape):
    return nd.reshape(tuple(int(d) for d in shape))


def nd_context(nd):
    ctx = nd.context
    return int(ctx.device_typeid), int(ctx.device_id)


def nd_copyto(src, dst):
    src.copyto(dst)
    return True


# -- extended Symbol surface -----------------------------------------------

def symbol_list_attr(sym, recursive):
    """Flattened [k0, v0, k1, v1, ...] (MXSymbolListAttr shape)."""
    d = sym.list_attr(recursive=bool(recursive))
    flat = []
    for k, v in sorted(d.items()):
        flat.append(str(k))
        flat.append(str(v))
    return flat


def symbol_num_outputs(sym):
    return len(sym.list_outputs())


def symbol_grad(sym, wrt):
    return sym.grad(list(wrt))


def executor_print(ex):
    return ex.debug_str()


# -- extended KVStore surface ----------------------------------------------

def kvstore_set_updater(kv, updater):
    """MXKVStoreSetUpdater: updater(key:int, recv, local) mutates local
    in place; `updater` is the C trampoline callable."""
    kv._set_updater(lambda k, recv, local: updater(int(k), recv, local))
    return True


def kvstore_save_optimizer_states(kv, fname):
    kv.save_optimizer_states(fname)
    return True


def kvstore_load_optimizer_states(kv, fname):
    kv.load_optimizer_states(fname)
    return True


def kvstore_send_command(kv, head, body):
    kv.send_command_to_servers(head, body)
    return True


def kvstore_num_dead_node(kv, node_id):
    return int(kv.num_dead_node(node_id))


# -- profiler / misc --------------------------------------------------------

def profiler_start(logdir):
    from . import profiler
    profiler.start(logdir)
    return True


def profiler_stop():
    from . import profiler
    profiler.stop()
    return True


def get_version():
    from . import __version__
    return str(__version__)


# -- completion of the reference entry-point surface ------------------------

def nd_save_raw(nd):
    """MXNDArraySaveRawBytes: self-describing single-array blob."""
    import io as _io
    buf = _io.BytesIO()
    np.save(buf, np.ascontiguousarray(nd.asnumpy()), allow_pickle=False)
    return buf.getvalue()


def nd_load_raw(data, dev_type, dev_id):
    import io as _io
    from .ndarray import NDArray
    arr = np.load(_io.BytesIO(bytes(data)), allow_pickle=False)
    return NDArray(arr, ctx=_ctx(dev_type, dev_id))


def nd_wait_to_read(nd):
    nd.wait_to_read()
    return True


def nd_wait_to_write(nd):
    nd.wait_to_write()
    return True


def symbol_from_file(path):
    from . import symbol
    return symbol.load(path)


def symbol_group(syms):
    from . import symbol
    return symbol.Group(list(syms))


def symbol_name(sym):
    return sym.name or ""


def symbol_infer_type(sym, keys, dtype_codes):
    """(complete, arg_codes, out_codes, aux_codes) with -1 = unknown."""
    # -1 input codes mean "no constraint" — never index the dtype table
    kwargs = {k: _np_dtype(c) for k, c in zip(keys, dtype_codes)
              if c >= 0}
    arg_t, out_t, aux_t = sym.infer_type(**kwargs)
    if arg_t is None:
        return False, [], [], []

    def codes(ts):
        out = []
        for t in ts:
            try:
                out.append(_dtype_code(t) if t is not None else -1)
            except ValueError:
                out.append(-1)
        return out

    return True, codes(arg_t), codes(out_t), codes(aux_t)


def dataiter_index(h):
    idx = getattr(h.batch, "index", None)
    if idx is None:
        return []
    return [int(i) for i in np.asarray(idx).reshape(-1)]


# imperative optimizer surface (MXOptimizerCreateOptimizer/Update/Free):
# a stateful updater closure per handle, state keyed by index
def optimizer_create(name, keys, vals):
    from .optimizer import Optimizer, get_updater
    opt = Optimizer.create_optimizer(name, **_parse_kwargs(keys, vals))
    return get_updater(opt)


def optimizer_update(updater, index, weight, grad):
    updater(int(index), grad, weight)
    return True


def recordio_writer_create(path):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "w")


def recordio_reader_create(path):
    from .recordio import MXRecordIO
    return MXRecordIO(path, "r")


def recordio_write(h, data):
    h.write(bytes(data))
    return True


def recordio_read(h):
    out = h.read()
    return b"" if out is None else out


def recordio_tell(h):
    return int(h.tell())


def recordio_reset(h):
    h.reset()
    return True


def recordio_close(h):
    h.close()
    return True


def kvstore_role():
    """'worker' | 'server' | 'scheduler' from the launcher env
    (reference DMLC_ROLE); single source of truth is kvstore_server."""
    import os
    from .kvstore_server import server_role
    if server_role():
        return "server"
    return os.environ.get("DMLC_ROLE",
                          os.environ.get("MXTPU_ROLE", "worker")) or "worker"


def kvstore_run_server(kv):
    """Enter the blocking server loop when launched in the server role
    (MXKVStoreRunServer; ``kv`` kept for ABI fidelity — the server is
    self-contained); returns immediately for workers."""
    from .kvstore_server import _init_kvstore_server_module, server_role
    if not server_role():
        return False
    _init_kvstore_server_module()
    return True


def init_ps_env(keys, vals):
    import os
    for k, v in zip(keys, vals):
        os.environ[str(k)] = str(v)
    return True


def notify_shutdown():
    """MXNotifyShutdown: drain the host engine before teardown."""
    from .engine import get_engine
    get_engine().wait_for_all()
    return True


# ---- boot-time registry publication ----------------------------------------
# A pure-C/C++ consumer calls MXTPUListOps/MXTPUGetOpInfo against the
# NATIVE registry (src/c_api.cc), which only the Python side can fill.
# When this bridge module boots inside the embedded interpreter, publish
# the full Python op registry through MXTPURegisterOp so runtime op
# discovery works for non-Python frontends (reference parity:
# MXSymbolListAtomicSymbolCreators sees every NNVM-registered op).
# ctypes.CDLL on the already-loaded .so resolves to the same module, so
# the registrations land in the globals the consumer binary reads.
try:
    from . import c_api as _c_api

    _c_api.publish_registry()
# mxtpu-lint: disable=swallowed-exception (never block the bridge boot
# over discovery metadata — the C ABI surface stays functional)
except Exception:
    pass
