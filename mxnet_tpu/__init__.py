"""mxnet_tpu: a TPU-native deep-learning framework with classic-MXNet
capabilities (NDArray, Symbol/Executor, Module, KVStore, data iterators)
rebuilt idiomatically on JAX/XLA/Pallas.  See SURVEY.md for the mapping
to the reference architecture."""

import os as _os

import jax as _jax

# The reference framework supports float64 end to end (mshadow type switch);
# enable x64 so dtype parity holds.  Weak-typed python scalars still keep
# float32 results in f32 graphs, so TPU perf paths are unaffected.
_jax.config.update("jax_enable_x64", True)

# MXTPU_PLATFORMS: framework-owned backend selector.  JAX_PLATFORMS is
# unusable for this — accelerator site plugins (axon sitecustomize)
# overwrite it at interpreter startup, so subprocesses (CLI tools, test
# workers) that exported JAX_PLATFORMS=cpu would still open the
# accelerator client and block while another process holds the chip.
if _os.environ.get("MXTPU_PLATFORMS"):
    try:
        _jax.config.update("jax_platforms", _os.environ["MXTPU_PLATFORMS"])
    # mxtpu-lint: disable=swallowed-exception (import-time guard: the
    # embedding process owns the backend; there is nowhere to report)
    except Exception:
        pass

from . import base
from .base import MXNetError
from . import aot

# MXTPU_COMPILE_CACHE=<dir>: persist XLA compiles across processes.
# Wired before any jit can run so the first compile of the process
# already reads/writes the cache (docs/how_to/startup.md).
aot.enable_from_env()
from .context import Context, cpu, cpu_pinned, current_context, gpu, tpu, num_devices
from . import engine
from . import random
from . import telemetry
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from .symbol import AttrScope, Variable, Group
from . import attribute
from . import executor
from . import executor_manager
from .executor import Executor
from . import initializer
from . import initializer as init  # reference: mx.init.Xavier() etc.
from . import optimizer
from . import optimizer as opt
from . import metric
from . import lr_scheduler
from . import callback
from . import misc
from . import monitor
from . import monitor as mon  # reference: mx.mon.Monitor
from . import profiler
from . import io
from . import recordio
from . import rnn_io
from . import image_io
from .image_io import ImageRecordIter
from . import cv

io.ImageRecordIter = ImageRecordIter  # reference exposes it under mx.io
from . import kvstore
from . import kvstore as kv
from . import kvstore_server
from . import model
from .model import FeedForward
from . import module
from . import module as mod
from . import visualization
from . import visualization as viz
# notebook (PandasLogger/LiveLearningCurve) is imported on demand, like
# the reference: `from mxnet_tpu.notebook import callback`
from . import test_utils
from . import operator
from . import rtc
from . import resource
from . import caffe
from . import sframe
from . import symbol_doc
from . import parallel
from . import models
from . import predict
from . import serve
from . import fleet
from . import torch_bridge
from . import c_api

# publish the op registry through the native C ABI so in-process
# non-Python frontends can discover ops (reference: frontends enumerate
# ops via MXSymbolListAtomicSymbolCreators at import)
try:
    c_api.publish_registry()
# mxtpu-lint: disable=swallowed-exception (native lib is optional;
# frontends fall back to the pure-Python registry)
except Exception:
    pass

__version__ = "0.1.0"
