"""mxnet_tpu: a TPU-native deep-learning framework with classic-MXNet
capabilities (NDArray, Symbol/Executor, Module, KVStore, data iterators)
rebuilt idiomatically on JAX/XLA/Pallas.  See SURVEY.md for the mapping
to the reference architecture."""

from . import base
from .base import MXNetError
from .context import Context, cpu, cpu_pinned, current_context, gpu, tpu, num_devices
from . import engine
from . import random
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from .symbol import AttrScope, Variable, Group
from . import executor
from .executor import Executor

__version__ = "0.1.0"
