"""Analytic FLOP counting over symbol graphs.

Sums the multiply-accumulate-dominant operators (Convolution,
Deconvolution, FullyConnected, FlashAttention, batched matmul) from a
symbol's graph given concrete input shapes; elementwise/normalization
ops are ignored (sub-percent contributors on real models).  One MAC
counts as 2 FLOPs.

The reference has no FLOP tooling; this powers the MFU line in
``bench.py`` (model FLOPs / step-time / chip peak), the metric the
TPU performance story is judged by ("How to Scale Your Model" usage).

Usage::

    fwd = count_flops(net, data=(32, 3, 224, 224))
    train_step = 3 * fwd          # fwd + ~2x for backward
"""

from __future__ import annotations

import numpy as np

__all__ = ["count_flops", "peak_flops_per_chip", "peak_hbm_bytes_per_chip",
           "gpt_token_flops", "gpt_prefill_flops"]


def _prod(t):
    out = 1
    for v in t:
        out *= int(v)
    return out


def count_flops(symbol, **input_shapes) -> int:
    """Forward-pass FLOPs of ``symbol`` under the given input shapes.

    Counts Convolution / Deconvolution / FullyConnected / FlashAttention
    / dot-family nodes; everything else is treated as free.
    """
    internals = symbol.get_internals()
    _, out_shapes, _ = internals.infer_shape_partial(**input_shapes)
    heads = internals._heads
    shape_of = {}  # (node, idx) -> shape
    for (node, idx), shp in zip(heads, out_shapes):
        shape_of[(node, idx)] = shp

    total = 0
    for node, idx in heads:
        if idx != 0 or node.is_variable:
            continue
        op_name = node.op.name
        params = node.params
        out_shp = shape_of[(node, idx)]
        in_shp = (shape_of.get(node.inputs[0]) if node.inputs else None)
        if out_shp is None or in_shp is None:
            continue
        if op_name == "Convolution":
            kh, kw = params.kernel
            groups = getattr(params, "num_group", 1) or 1
            # output spatial positions x per-position dot of size
            # kh*kw*Cin/groups; layout-agnostic via element counts
            cin = (in_shp[-1] if getattr(params, "layout", "NCHW") == "NHWC"
                   else in_shp[1])
            total += 2 * _prod(out_shp) * kh * kw * cin // groups
        elif op_name == "Deconvolution":
            # transposed conv MACs scale with the INPUT extent: every
            # input position scatters a kh*kw*Cout patch
            kh, kw = params.kernel
            groups = getattr(params, "num_group", 1) or 1
            total += (2 * _prod(in_shp) * kh * kw
                      * params.num_filter // groups)
        elif op_name == "FullyConnected":
            in_dim = _prod(in_shp[1:])
            total += 2 * _prod(out_shp) * in_dim
        elif op_name == "FlashAttention":
            # (B, H, T, D): QK^T and PV are each 2*B*H*T^2*D
            b, h, t, d = in_shp
            total += 4 * b * h * t * t * d
        elif op_name in ("dot", "batch_dot", "linalg_gemm2"):
            rhs_shp = shape_of.get(node.inputs[1])
            if rhs_shp:
                # contraction length, transpose-flag agnostic:
                # |lhs|*|rhs| = (m k)(k n) and |out| = m n  =>  k^2
                k2 = (_prod(in_shp) * _prod(rhs_shp)) / max(_prod(out_shp), 1)
                total += int(2 * _prod(out_shp) * (k2 ** 0.5))
    return int(total)


def gpt_token_flops(n_layers, d_model, num_heads, head_dim, kv_heads,
                    vocab, context, d_ff=None, swiglu=False):
    """Analytic forward FLOPs for ONE token of a normalized ``gpt()``
    checkpoint attending over ``context`` cached positions (GQA-aware).

    Counts the matmul-dominant terms only — QKV/out projections, the
    per-head score and weighted-sum dots against the KV cache, the MLP
    (gate included under ``swiglu``), and the LM head — matching the
    :func:`count_flops` convention (1 MAC = 2 FLOPs, elementwise free).
    This is the per-token MFU denominator for serve-side attribution
    when a backend has no ``cost_analysis()``; the serve programs pad
    to bucket shapes, so pass the PADDED context (table capacity), not
    the live sequence length, to match compiled-program cost.
    """
    d_attn = num_heads * head_dim
    d_kv = kv_heads * head_dim
    d_ff = int(d_ff) if d_ff else 4 * d_model
    per_layer = 2 * d_model * d_attn          # Q projection
    per_layer += 2 * 2 * d_model * d_kv       # K + V projections (GQA)
    per_layer += 2 * d_attn * d_model         # output projection
    # scores (q . k) and weighted sum (p . v), 2 FLOPs/MAC each, over
    # the full padded context
    per_layer += 4 * num_heads * head_dim * int(context)
    mlp = 2 * d_model * d_ff + 2 * d_ff * d_model      # up + down
    if swiglu:
        mlp += 2 * d_model * d_ff                      # gate
    per_layer += mlp
    return int(n_layers) * per_layer + 2 * d_model * int(vocab)


def gpt_prefill_flops(n_layers, d_model, num_heads, head_dim, kv_heads,
                      vocab, seq_len, d_ff=None, swiglu=False,
                      logits_positions=None):
    """Analytic forward FLOPs for a dense ``seq_len``-token prefill of a
    normalized ``gpt()`` checkpoint.

    The serve prefill/chunk programs materialize the full (masked)
    TxT score matrix, so attention costs ``context = seq_len`` per
    position — not the triangle — which is what ``cost_analysis()``
    reports for the compiled program.  ``logits_positions`` bounds the
    LM-head term (1 for last-position-only programs; defaults to all
    positions).
    """
    T = int(seq_len)
    per_tok = gpt_token_flops(n_layers, d_model, num_heads, head_dim,
                              kv_heads, vocab, context=T, d_ff=d_ff,
                              swiglu=swiglu)
    head = 2 * d_model * int(vocab)
    total = T * (per_tok - head)
    n_logits = T if logits_positions is None else int(logits_positions)
    return total + n_logits * head


# bf16 peak FLOP/s per chip by device_kind substring (public figures)
_PEAKS = [
    ("v6e", 918e12), ("v6", 918e12),
    ("v5p", 459e12), ("v5 lite", 197e12), ("v5e", 197e12), ("v5", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def peak_flops_per_chip(device=None):
    """Peak bf16 FLOP/s for the local accelerator, or None if unknown."""
    import jax

    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if d.platform != "tpu":
        return None
    for tag, peak in _PEAKS:
        if tag in kind:
            return peak
    return None


# peak HBM bandwidth (bytes/s) per chip by device_kind substring
# (public figures) — the MBU denominator
_HBM_PEAKS = [
    ("v6e", 1640e9), ("v6", 1640e9),
    ("v5p", 2765e9), ("v5 lite", 819e9), ("v5e", 819e9), ("v5", 2765e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
]


def peak_hbm_bytes_per_chip(device=None):
    """Peak HBM bandwidth (bytes/s) for the local accelerator, or None
    if unknown — memory-bandwidth-utilization's denominator, the
    figure decode (bandwidth-bound) is judged against."""
    import jax

    d = device or jax.devices()[0]
    kind = getattr(d, "device_kind", "").lower()
    if d.platform != "tpu":
        return None
    for tag, peak in _HBM_PEAKS:
        if tag in kind:
            return peak
    return None
