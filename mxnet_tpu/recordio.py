"""RecordIO: packed record format + readers/writers.

Rebuild of python/mxnet/recordio.py and dmlc-core's recordio framing as
used by the reference data pipeline (src/io/iter_image_recordio.cc).
Binary-compatible with the reference format: records framed by the magic
``0xced7230a`` + a length-encoded header word, payload padded to 4-byte
boundaries, plus the IRHeader (flag, label, id, id2) image-record header
used by im2rec — so .rec datasets packed for the reference load here
unchanged.
"""

from __future__ import annotations

import os
import struct

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xCED7230A
_ENC_MASK = 0x1FFFFFFF


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _dec_flag(header):
    return header >> 29


def _dec_length(header):
    return header & _ENC_MASK


class MXRecordIO:
    """Sequential record reader/writer (recordio.py MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("invalid flag " + self.flag)

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def reset(self):
        self.close()
        self.open()

    def write(self, buf: bytes):
        assert self.writable
        self.handle.write(struct.pack("<II", _MAGIC, _encode_lrec(0, len(buf))))
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        head = self.handle.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        if magic != _MAGIC:
            raise IOError("invalid record magic")
        length = _dec_length(lrec)
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf

    def tell(self):
        return self.handle.tell()

    def __del__(self):
        self.close()

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        d["_pos"] = self.handle.tell() if self.handle else 0
        return d

    def __setstate__(self, d):
        pos = d.pop("_pos", 0)
        self.__dict__.update(d)
        self.open()
        if not self.writable:
            self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access record IO via an .idx sidecar (recordio.py:86)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)
        if not self.writable and os.path.isfile(idx_path):
            with open(idx_path) as f:
                for line in f:
                    key, pos = line.strip().split("\t")
                    key = key_type(key)
                    self.idx[key] = int(pos)
                    self.keys.append(key)

    def close(self):
        if self.handle is not None and self.writable:
            with open(self.idx_path, "w") as f:
                for key in self.keys:
                    f.write(f"{key}\t{self.idx[key]}\n")
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


class IRHeader:
    """Image-record header (recordio.py IRHeader): flag, label, id, id2."""

    _FMT = "<IfQQ"

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


def pack(header: IRHeader, s: bytes) -> bytes:
    """Pack a (header, payload) image record (recordio.py pack)."""
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)):
        label = np.asarray(label, dtype=np.float32)
        header = IRHeader(len(label), 0.0, header.id, header.id2)
        return struct.pack(IRHeader._FMT, header.flag, header.label,
                           header.id, header.id2) + label.tobytes() + s
    return struct.pack(IRHeader._FMT, int(header.flag), float(label),
                       int(header.id), int(header.id2)) + s


def unpack(s: bytes):
    """Unpack a record into (IRHeader, payload) (recordio.py unpack)."""
    flag, label, id_, id2 = struct.unpack(IRHeader._FMT,
                                          s[:struct.calcsize(IRHeader._FMT)])
    s = s[struct.calcsize(IRHeader._FMT):]
    header = IRHeader(flag, label, id_, id2)
    if flag > 0:
        label = np.frombuffer(s[:flag * 4], dtype=np.float32)
        header = IRHeader(flag, label, id_, id2)
        s = s[flag * 4:]
    return header, s


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array as a compressed record (recordio.py pack_img)."""
    import cv2

    encode_params = None
    if img_fmt in (".jpg", ".jpeg"):
        encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
    elif img_fmt == ".png":
        encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
    ret, buf = cv2.imencode(img_fmt, img, encode_params)
    if not ret:
        raise RuntimeError("failed to encode image")
    return pack(header, buf.tobytes())


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, decoded image) (recordio.py)."""
    import cv2

    header, img_bytes = unpack(s)
    img = cv2.imdecode(np.frombuffer(img_bytes, dtype=np.uint8), iscolor)
    return header, img
