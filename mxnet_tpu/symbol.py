"""Symbolic graph construction.

Rebuild of the reference Symbol layer (include/mxnet/symbolic.h:40-317,
src/symbol/symbol.cc, static_graph.cc; Python frontend
python/mxnet/symbol.py).  A Symbol is a list of heads over shared
``Node``s; composition auto-creates variable nodes for unbound op
arguments and auxiliary states (reference Compose semantics).  Graph JSON
save/load keeps the reference's two-artifact checkpoint contract
(symbol JSON + named param blob, SURVEY.md §5).

Op-creating functions (``mx.sym.Convolution`` etc.) are generated from the
op registry at import time, mirroring python/mxnet/symbol.py:999-1120.
"""

from __future__ import annotations

import builtins
import json
import sys
import threading

import numpy as np

from .base import MXNetError, dtype_name, np_dtype, numeric_types
from .ops import OP_REGISTRY

__all__ = ["Symbol", "Variable", "Group", "load", "load_json", "AttrScope",
           "NameManager", "Prefix"]


class AttrScope:
    """Attribute scope propagated onto created symbols
    (python/mxnet/attribute.py; carries ctx_group / force_mirroring /
    lr_mult-style attrs)."""

    _current = threading.local()

    def __init__(self, **attrs):
        self._attrs = {k: str(v) for k, v in attrs.items()}
        self._old = None

    @classmethod
    def current_attrs(cls) -> dict:
        cur = getattr(cls._current, "value", None)
        return dict(cur._attrs) if cur is not None else {}

    def __enter__(self):
        self._old = getattr(AttrScope._current, "value", None)
        merged = dict(self._old._attrs) if self._old else {}
        merged.update(self._attrs)
        self._merged_attrs = self._attrs
        self._attrs = merged
        AttrScope._current.value = self
        return self

    def __exit__(self, *exc):
        self._attrs = self._merged_attrs
        AttrScope._current.value = self._old
        return False

    def get(self, attr):
        """Merge user-passed attrs over this scope's attrs (reference
        attribute.py:26-44): scope values are defaults, explicit symbol
        attrs win."""
        if self._attrs:
            ret = self._attrs.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}


class _NameGet:
    """``NameManager.get()`` (classmethod style) returns the current
    manager — this build's internal accessor; ``manager.get(name, hint)``
    (instance style) is the reference canonical-name API
    (python/mxnet/name.py:16): the user name wins, else an auto name
    from the hint."""

    def __get__(self, obj, objtype):
        if obj is None:
            return objtype._current_manager
        return obj._ref_get


class NameManager:
    """Automatic unique naming (python/mxnet/name.py)."""

    _current = threading.local()

    get = _NameGet()

    def __init__(self):
        self._counter = {}

    @classmethod
    def _current_manager(cls):
        if getattr(cls._current, "value", None) is None:
            cls._current.value = NameManager()
        return cls._current.value

    def next_name(self, hint: str) -> str:
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return f"{hint}{idx}"

    def _ref_get(self, name, hint):
        """Reference name.py:16-38 canonical-name rule: a truthy user
        name wins, else an auto name from the hint."""
        return name if name else self.next_name(hint)

    def __enter__(self):
        self._old = getattr(NameManager._current, "value", None)
        NameManager._current.value = self
        return self

    def __exit__(self, *exc):
        NameManager._current.value = self._old
        return False


class Prefix(NameManager):
    """NameManager that prepends a prefix to every auto name
    (python/mxnet/name.py Prefix)."""

    def __init__(self, prefix: str):
        super().__init__()
        self._prefix = prefix

    def next_name(self, hint: str) -> str:
        return self._prefix + super().next_name(hint)

    def _ref_get(self, name, hint):
        """Reference name.py:73-75: the prefix applies to USER names
        too (``super().get`` then prepend)."""
        if name:
            return self._prefix + name
        return self.next_name(hint)   # already prefixed


class Node:
    """One graph node: an op application or a variable (symbolic.h Node)."""

    __slots__ = ("op", "name", "attrs", "params", "inputs", "_id")

    def __init__(self, op, name, attrs=None, params=None, inputs=None):
        self.op = op  # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs or {})
        self.params = params
        self.inputs = list(inputs or [])  # [(Node, out_index)]

    @property
    def is_variable(self):
        return self.op is None

    def num_outputs(self):
        return 1 if self.is_variable else self.op.num_outputs(self.params)

    def __repr__(self):
        kind = "var" if self.is_variable else self.op.name
        return f"<Node {kind}:{self.name}>"


def _topo_order(head_nodes):
    """Post-order DFS over unique nodes (static_graph.cc topo sort)."""
    seen = set()
    order = []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for (src, _) in node.inputs:
            visit(src)
        order.append(node)

    for n in head_nodes:
        visit(n)
    return order


# (graph-head ids, wrt) -> registered grad-op name.  Bounded: grad ops
# close over their base graph, so unbounded registration would leak graphs
# when callers rebuild symbols per iteration; eviction only drops the
# registry entry — already-built grad symbols hold the op directly.
_GRAD_OP_CACHE = {}
_GRAD_OP_CACHE_MAX = 64


class Symbol:
    """A list of output heads over a shared node graph."""

    __slots__ = ("_heads",)

    def __init__(self, heads):
        self._heads = list(heads)  # [(Node, out_index)]

    # -- introspection -----------------------------------------------------
    @property
    def name(self):
        if len(self._heads) == 1:
            return self._heads[0][0].name
        return None

    def _topo(self):
        return _topo_order([n for n, _ in self._heads])

    def list_arguments(self):
        """Names of argument variables in topo order (symbolic.h:132).

        Auxiliary-state variables are excluded (they have the node attr
        ``__aux__``)."""
        return [n.name for n in self._topo()
                if n.is_variable and "__aux__" not in n.attrs]

    def list_outputs(self):
        out = []
        for node, idx in self._heads:
            if node.is_variable:
                out.append(node.name)
            else:
                out.append(f"{node.name}_{node.op.list_outputs(node.params)[idx]}")
        return out

    def list_auxiliary_states(self):
        return [n.name for n in self._topo()
                if n.is_variable and "__aux__" in n.attrs]

    def get_internals(self) -> "Symbol":
        """Symbol exposing every internal output (symbolic.h GetInternals)."""
        heads = []
        for node in self._topo():
            for i in range(node.num_outputs()):
                heads.append((node, i))
        return Symbol(heads)

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise ValueError(f"no output named {index!r}; outputs: {names}")
            index = names.index(index)
        return Symbol([self._heads[index]])

    def __len__(self):
        return len(self._heads)

    def __iter__(self):
        return (self[i] for i in range(len(self._heads)))

    # -- attributes --------------------------------------------------------
    def attr(self, key):
        if len(self._heads) == 1:
            return self._heads[0][0].attrs.get(key)
        return None

    def _set_attr(self, **kwargs):
        for node, _ in self._heads:
            node.attrs.update({k: str(v) for k, v in kwargs.items()})

    def attr_dict(self):
        out = {}
        for node in self._topo():
            if node.attrs:
                out[node.name] = dict(node.attrs)
        return out

    def list_attr(self, recursive=False):
        """All attributes of this symbol (reference symbol.py:255).

        ``recursive=True`` walks descendants with ``<node>_``-prefixed
        keys (MXSymbolListAttr); shallow returns only the head node's
        own attrs, un-prefixed (MXSymbolListAttrShallow)."""
        if not recursive:
            if len(self._heads) == 1:
                return dict(self._heads[0][0].attrs)
            return {}
        out = {}
        for node in self._topo():
            for k, v in node.attrs.items():
                out[f"{node.name}_{k}"] = v
        return out

    # -- composition -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: bind this symbol's free variables to new inputs
        (symbolic.h Compose).  Returns a new Symbol; the graph is copied so
        the original stays reusable."""
        name = kwargs.pop("name", None)
        mapping = {}
        arg_names = self.list_arguments()
        if args:
            if kwargs:
                raise MXNetError("compose accepts positional or keyword args, not both")
            if len(args) > len(arg_names):
                raise MXNetError("too many positional arguments")
            for argname, sym in zip(arg_names, args):
                mapping[argname] = sym
        for k, v in kwargs.items():
            if k not in arg_names:
                raise MXNetError(f"unknown argument {k!r}; args: {arg_names}")
            mapping[k] = v
        copies = {}

        def copy_node(node):
            if id(node) in copies:
                return copies[id(node)]
            if node.is_variable and node.name in mapping:
                head_node, head_idx = mapping[node.name]._heads[0]
                if head_idx != 0:
                    # splice a pass-through of that output via _copy
                    new = Node(OP_REGISTRY.get("_copy"), node.name, {},
                               None, [(head_node, head_idx)])
                else:
                    new = head_node
            else:
                new = Node(node.op, node.name, node.attrs, node.params,
                           [(copy_node(s), i) for s, i in node.inputs])
            copies[id(node)] = new
            return new

        return Symbol([(copy_node(n), i) for n, i in self._heads])

    # -- shape / dtype inference -------------------------------------------
    def infer_shape(self, *args, **kwargs):
        return self._infer_shape_impl(False, *args, **kwargs)

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, shp in zip(arg_names, args):
                if shp is not None:
                    known[name] = tuple(shp)
        for k, v in kwargs.items():
            if k not in arg_names and k not in self.list_auxiliary_states():
                raise MXNetError(f"infer_shape: unknown argument {k!r}")
            known[k] = tuple(v)
        shapes = _infer_graph(self._topo(), known, "shape", partial)
        if shapes is None:
            return None, None, None
        arg_shapes = [shapes["var", n] for n in arg_names]
        aux_shapes = [shapes["var", n] for n in self.list_auxiliary_states()]
        out_shapes = [shapes["out", id(n), i] for n, i in self._heads]
        return arg_shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        known = {}
        if args:
            for name, dt in zip(arg_names, args):
                if dt is not None:
                    known[name] = np_dtype(dt)
        for k, v in kwargs.items():
            known[k] = np_dtype(v)
        types = _infer_graph(self._topo(), known, "dtype", False)
        if types is None:
            return None, None, None
        arg_types = [types["var", n] for n in arg_names]
        aux_types = [types["var", n] for n in self.list_auxiliary_states()]
        out_types = [types["out", id(n), i] for n, i in self._heads]
        return arg_types, out_types, aux_types

    def grad(self, wrt):
        """Gradient symbol (reference symbol.py:859 `Symbol.grad` /
        `MXSymbolGrad` c_api.cc:770 -> Symbol::Grad).

        Only meaningful on loss symbols: returns a new Symbol with the
        same argument names whose outputs are d(loss)/d(arg) for each
        name in ``wrt`` (head gradients are ones, the loss-layer
        backward convention).  The gradient computation is ``jax.vjp``
        over the traced graph, so it is itself traceable/jittable and
        differentiable again (second-order — beyond the reference).
        """
        from .ops.op import OpDef

        base = self
        wrt = [wrt] if isinstance(wrt, str) else list(wrt)
        arg_names = base.list_arguments()
        aux_names = base.list_auxiliary_states()
        for w in wrt:
            if w not in arg_names:
                raise MXNetError(
                    f"grad: {w!r} is not an argument of this symbol "
                    f"(arguments: {arg_names})")
        # one registered op per (graph head, wrt): repeated grad() calls
        # in a loop reuse it instead of growing the registry
        cache_key = (tuple((id(n), i) for n, i in self._heads), tuple(wrt))
        cached_name = _GRAD_OP_CACHE.get(cache_key)
        if cached_name is not None:
            bound = {a: Variable(a) for a in arg_names}
            return _create(cached_name, [], {**bound, "name": cached_name})
        has_rng = any(not n.is_variable and n.op.need_rng
                      for n in base._topo())

        class _GradOp(OpDef):
            need_rng = has_rng

            def __init__(self):
                self._graph = None

            def list_arguments(self, params):
                return list(arg_names)

            def list_outputs(self, params):
                return [f"{w}_grad" for w in wrt]

            def list_auxiliary_states(self, params):
                return list(aux_names)

            def infer_shape(self, params, in_shapes):
                known = {n: s for n, s in zip(arg_names, in_shapes)
                         if s is not None}
                arg_shapes, _, aux_shapes = base.infer_shape(**known)
                outs = [arg_shapes[arg_names.index(w)] for w in wrt]
                return list(arg_shapes), outs, list(aux_shapes)

            def infer_dtype(self, params, in_dtypes):
                ins, _, auxs = OpDef.infer_dtype(self, params, in_dtypes)
                return ins, [ins[arg_names.index(w)] for w in wrt], auxs

            def forward(self, params, inputs, aux, train, key):
                import jax
                import jax.numpy as jnp

                from .executor import _CompiledGraph

                if self._graph is None:
                    self._graph = _CompiledGraph(base)
                graph = self._graph
                arg_vals = dict(zip(arg_names, inputs))
                aux_vals = dict(zip(aux_names, aux))

                def f(wvals):
                    av = dict(arg_vals)
                    av.update(zip(wrt, wvals))
                    outs, _ = graph(av, aux_vals, key, train)
                    return tuple(outs)

                outs, vjp = jax.vjp(f, [arg_vals[w] for w in wrt])
                grads = vjp(tuple(jnp.ones_like(o) for o in outs))[0]
                return list(grads), list(aux)

        op = _GradOp()
        gname = f"_grad_{id(op):x}"
        op.name = gname
        op.serializable = False  # process-local closure over `base`
        OP_REGISTRY.register(gname, op)
        _GRAD_OP_CACHE[cache_key] = gname
        while len(_GRAD_OP_CACHE) > _GRAD_OP_CACHE_MAX:
            old_key = next(iter(_GRAD_OP_CACHE))
            OP_REGISTRY.remove(_GRAD_OP_CACHE.pop(old_key))
        bound = {a: Variable(a) for a in arg_names}
        return _create(gname, [], {**bound, "name": gname})

    # -- serialization (static_graph.cc:601-616 JSON contract) --------------
    def __reduce__(self):
        """Pickle via the JSON graph (reference symbol.py __getstate__:
        the handle is process-local; the graph is the state).  Lets
        objects that CARRY a symbol — an Optimizer created with
        ``sym=`` riding to a kvstore server, a checkpointed module —
        pickle without dragging registry lambdas along."""
        return (load_json, (self.tojson(),))

    def tojson(self) -> str:
        nodes = self._topo()
        for n in nodes:
            if not n.is_variable and not getattr(n.op, "serializable", True):
                raise MXNetError(
                    f"symbol contains process-local op {n.op.name!r} "
                    "(e.g. a Symbol.grad result) and cannot be serialized; "
                    "save the base symbol and re-derive the gradient after "
                    "loading")
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        out_nodes = []
        for n in nodes:
            entry = {
                "op": "null" if n.is_variable else n.op.name,
                "name": n.name,
                "inputs": [[node_ids[id(s)], i] for s, i in n.inputs],
            }
            if n.attrs:
                entry["attr"] = dict(n.attrs)
            if n.params is not None:
                entry["param"] = n.params.to_dict()
            out_nodes.append(entry)
        graph = {
            "nodes": out_nodes,
            "arg_nodes": [i for i, n in enumerate(nodes) if n.is_variable],
            "heads": [[node_ids[id(n)], idx] for n, idx in self._heads],
            "attrs": {"mxnet_tpu_version": 1},
        }
        return json.dumps(graph, indent=2)

    def save(self, fname: str):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # -- binding (executor factory; implemented in executor.py) -------------
    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        """Bind to caller-provided arrays (reference symbol.py:724).

        ``shared_exec`` is accepted for reference API compatibility but
        has no effect here: the reference shares internal activation
        memory between executors (GraphStoragePool), which XLA buffer
        assignment owns in this build, and ``bind``'s argument arrays are
        supplied by the caller — pass the SAME NDArray objects to both
        executors for parameter sharing, or use ``simple_bind(...,
        shared_exec=...)`` which does that automatically."""
        from .executor import Executor

        return Executor._bind(self, ctx, args, args_grad, grad_req, aux_states,
                              group2ctx=group2ctx, shared_exec=shared_exec)

    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, **kwargs):
        """Infer shapes, allocate arrays, bind (reference symbol.py:643).

        With ``shared_exec``, parameter/gradient/aux arrays whose name,
        shape, dtype and context match the shared executor's are REUSED
        (the same NDArray objects — updates are visible to both); inputs
        named in ``kwargs`` are always freshly allocated."""
        from .executor import Executor

        return Executor._simple_bind(self, ctx, grad_req, type_dict, group2ctx,
                                     shared_exec, **kwargs)

    # -- arithmetic --------------------------------------------------------
    def __add__(self, other):
        return _sym_ufunc(self, other, "_plus", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return _sym_ufunc(self, other, "_minus", "_minus_scalar")

    def __rsub__(self, other):
        return _sym_ufunc(self, other, None, "_rminus_scalar")

    def __mul__(self, other):
        return _sym_ufunc(self, other, "_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return _sym_ufunc(self, other, "_div", "_div_scalar")

    def __rtruediv__(self, other):
        return _sym_ufunc(self, other, None, "_rdiv_scalar")

    def __pow__(self, other):
        return _sym_ufunc(self, other, "_power", "_power_scalar")

    def __neg__(self):
        return _create("negative", [self], {})

    def __repr__(self):
        name = self.name
        return f"<Symbol {name}>" if name else f"<Symbol group of {len(self)}>"

    def __copy__(self):
        return Symbol(list(self._heads))

    def debug_str(self):
        lines = []
        for n in self._topo():
            kind = "Variable" if n.is_variable else n.op.name
            ins = ", ".join(f"{s.name}[{i}]" for s, i in n.inputs)
            lines.append(f"{kind} {n.name}({ins})")
        return "\n".join(lines)


def _sym_ufunc(lhs, rhs, op_name, scalar_op_name):
    if isinstance(rhs, Symbol):
        if op_name is None:
            raise TypeError("operation not supported")
        return _create(op_name, [lhs, rhs], {})
    if isinstance(rhs, (int, float, np.generic)):
        return _create(scalar_op_name, [lhs], {"scalar": float(rhs)})
    raise TypeError(f"unsupported operand type {type(rhs)}")


def _mixed_binary(left, right, op, scalar_op, rscalar_op, py_op, fname):
    """Symbol/Number dispatch of the reference module-level helpers
    (symbol.py:1122-1195 pow/maximum/minimum)."""
    num = numeric_types
    if isinstance(left, Symbol) and isinstance(right, Symbol):
        return _create(op, [left, right], {})
    if isinstance(left, Symbol) and isinstance(right, num):
        return _create(scalar_op, [left], {"scalar": float(right)})
    if isinstance(left, num) and isinstance(right, Symbol):
        return _create(rscalar_op, [right], {"scalar": float(left)})
    if isinstance(left, num) and isinstance(right, num):
        return py_op(left, right)
    raise TypeError(
        f"{fname}: types ({type(left)}, {type(right)}) not supported")


def pow(base, exp):  # noqa: A001 - reference API name
    """base ** exp with Symbol/Number operands (reference symbol.py:1122)."""
    return _mixed_binary(base, exp, "_power", "_power_scalar",
                         "_rpower_scalar", lambda a, b: a ** b, "pow")


def maximum(left, right):
    """Elementwise max with Symbol/Number operands (symbol.py:1148)."""
    # builtins.max explicitly: the registry creator for op "max" shadows
    # the builtin in this module's namespace after _init_symbol_module
    return _mixed_binary(left, right, "_maximum", "_maximum_scalar",
                         "_maximum_scalar", builtins.max, "maximum")


def minimum(left, right):
    """Elementwise min with Symbol/Number operands (symbol.py:1174)."""
    return _mixed_binary(left, right, "_minimum", "_minimum_scalar",
                         "_minimum_scalar", builtins.min, "minimum")


def _infer_graph(topo, known, what, partial):
    """Forward inference over the graph; two passes so late-discovered
    variable values (e.g. FC weight shapes) propagate."""
    import ast as _ast

    values = {}  # ("var", name) | ("out", node_id, idx) -> value
    for n in topo:
        if n.is_variable:
            v = known.get(n.name)
            if v is None and what == "shape" and "__shape__" in n.attrs:
                v = tuple(_ast.literal_eval(n.attrs["__shape__"]))
            values["var", n.name] = v
    for _ in range(2):
        progress = False
        for node in topo:
            if node.is_variable:
                values["out", id(node), 0] = values["var", node.name]
                continue
            n_args = len(node.op.list_arguments(node.params))
            in_vals = []
            for src, idx in node.inputs[:n_args]:
                v = (values.get(("var", src.name)) if src.is_variable
                     else values.get(("out", id(src), idx)))
                in_vals.append(v)
            try:
                if what == "shape":
                    comp_in, outs, auxs = node.op.infer_shape(node.params, in_vals)
                else:
                    comp_in, outs, auxs = node.op.infer_dtype(node.params, in_vals)
            except (ValueError, MXNetError) as e:
                if partial:
                    for i in range(node.num_outputs()):
                        values.setdefault(("out", id(node), i), None)
                    continue
                if isinstance(e, MXNetError):
                    raise
                raise MXNetError(f"infer_{what} at node {node.name}: {e}") from e
            # aux-state variables trail the argument inputs on the node
            for (src, idx), v in zip(node.inputs[n_args:], auxs):
                if src.is_variable and v is not None and values.get(("var", src.name)) is None:
                    values["var", src.name] = tuple(v) if what == "shape" else v
                    progress = True  # aux var nodes need a second pass
            # write back completed input values to variable sources
            for (src, idx), v in zip(node.inputs[:n_args], comp_in):
                if src.is_variable and v is not None:
                    prev = values.get(("var", src.name))
                    if prev is None:
                        values["var", src.name] = tuple(v) if what == "shape" else v
                        progress = True
                    elif what == "shape" and tuple(prev) != tuple(v):
                        raise MXNetError(
                            f"inferred shape conflict for {src.name}: {prev} vs {v}")
            for i, v in enumerate(outs):
                values["out", id(node), i] = v
        if not progress:
            break
    missing = [k for k, v in values.items() if v is None]
    if missing and not partial:
        names = [k[1] for k in missing if k[0] == "var"]
        raise MXNetError(f"infer_{what}: insufficient information for {names}")
    return values


# -- constructors ------------------------------------------------------------
def Variable(name, attr=None, shape=None, **kwargs) -> Symbol:
    """Create a variable symbol (python/mxnet/symbol.py Variable)."""
    attrs = AttrScope.current_attrs()
    if attr:
        attrs.update({k: str(v) for k, v in attr.items()})
    for k, v in kwargs.items():
        attrs["__" + k + "__"] = str(v)
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    return Symbol([(Node(None, name, attrs), 0)])


def Group(symbols) -> Symbol:
    heads = []
    for s in symbols:
        heads.extend(s._heads)
    return Symbol(heads)


def load_json(json_str: str) -> Symbol:
    graph = json.loads(json_str)
    nodes = []
    for entry in graph["nodes"]:
        if entry["op"] == "null":
            node = Node(None, entry["name"], entry.get("attr"))
        else:
            op = OP_REGISTRY.get(entry["op"])
            params = op.make_params(entry.get("param", {}))
            node = Node(op, entry["name"], entry.get("attr"), params,
                        [(nodes[i], idx) for i, idx, *_ in entry["inputs"]])
        nodes.append(node)
    return Symbol([(nodes[i], idx) for i, idx in graph["heads"]])


def load(fname: str) -> Symbol:
    with open(fname) as f:
        return load_json(f.read())


# -- op symbol creation ------------------------------------------------------
def _create(op_name, sym_inputs, kwargs):
    """Create an op node; auto-create variables for unbound args and aux
    states (reference symbol.cc CreateFromAtomicSymbol + Compose)."""
    op = OP_REGISTRY.get(op_name)
    name = kwargs.pop("name", None)
    attr = kwargs.pop("attr", None)
    # split kwargs into symbol inputs vs op params
    sym_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, Symbol)}
    param_kwargs = {k: v for k, v in kwargs.items() if not isinstance(v, Symbol)}
    # var-arg ops (Concat/ElementWiseSum/Crop) get num_args from the
    # positional count when not given — the reference key_var_num_args
    # auto-fill (python/mxnet/symbol.py:1056-1058), opt-in per op
    kv = op.key_var_num_args
    if kv and kv not in param_kwargs and sym_inputs:
        param_kwargs[kv] = len(sym_inputs)
    params = op.make_params(param_kwargs)
    arg_names = op.list_arguments(params)
    if name is None:
        name = NameManager.get().next_name(op.name.lower())
    attrs = AttrScope.current_attrs()
    if attr:
        attrs.update({k: str(v) for k, v in attr.items()})

    bound = {}
    if sym_inputs:
        if len(sym_inputs) > len(arg_names):
            raise MXNetError(f"{op_name}: too many inputs ({len(sym_inputs)} > "
                             f"{len(arg_names)})")
        for argname, sym in zip(arg_names, sym_inputs):
            bound[argname] = sym
    for k, v in sym_kwargs.items():
        if k not in arg_names:
            raise MXNetError(f"{op_name}: unknown input {k!r}; inputs: {arg_names}")
        if k in bound:
            raise MXNetError(f"{op_name}: input {k!r} bound twice")
        bound[k] = v

    inputs = []
    for argname in arg_names:
        if argname in bound:
            inputs.append(bound[argname]._heads[0])
        else:
            var = Node(None, f"{name}_{argname}", AttrScope.current_attrs())
            inputs.append((var, 0))
    node = Node(op, name, attrs, params, inputs)
    # auxiliary-state variables hang off the node for discovery
    for aux_name in op.list_auxiliary_states(params):
        var = Node(None, f"{name}_{aux_name}", {"__aux__": "1"})
        node.inputs.append((var, 0))
    return Symbol([(node, i) for i in range(op.num_outputs(params))])


def _make_symbol_function(op_name):
    op = OP_REGISTRY.get(op_name)

    def creator(*args, **kwargs):
        sym_inputs = []
        for a in args:
            if not isinstance(a, Symbol):
                raise TypeError(f"{op_name}: positional args must be Symbols")
            sym_inputs.append(a)
        return _create(op_name, sym_inputs, kwargs)

    creator.__name__ = op_name
    creator.__qualname__ = op_name
    creator.__doc__ = (
        f"Symbolic op ``{op_name}``"
        + (f"\n{op.param_cls.__doc__}" if op.param_cls else "")
    )
    return creator


def Custom(*args, op_type=None, **kwargs):
    """Generic custom-op invoker (src/operator/custom.cc `Custom` registration;
    python/mxnet/operator.py usage ``mx.sym.Custom(..., op_type=name)``):
    dispatches to the CustomOpProp registered under ``op_type``."""
    if op_type is None:
        raise TypeError("Custom requires op_type=<registered custom op name>")
    if op_type not in OP_REGISTRY:
        raise MXNetError(f"Custom op {op_type!r} is not registered")
    return _make_symbol_function(op_type)(*args, **kwargs)


def _init_symbol_module():
    mod = sys.modules[__name__]
    # the Symbol/Number dispatch helpers (reference symbol.py:1122-1195)
    # take precedence over raw registry creators of the same name
    keep = {"pow": pow, "maximum": maximum, "minimum": minimum}
    for name in OP_REGISTRY.list():
        fn = _make_symbol_function(name)
        setattr(mod, name, fn)
        canonical = OP_REGISTRY.get(name)
        if canonical.name.lower() == name:
            setattr(mod, canonical.name, fn)
    for name, fn in keep.items():
        setattr(mod, name, fn)


_init_symbol_module()
