"""1-bit and 2-bit gradient compression with error feedback.

Rebuild of the capability later MXNet shipped as
src/kvstore/gradient_compression.cc (the 2016 reference predates it):
each gradient element quantizes to {-threshold, 0, +threshold} — two
bits — and the quantization error is kept worker-side and added to the
NEXT gradient (error feedback), so the update sequence stays unbiased
and SGD converges.  Wire payloads shrink 16x vs float32, which is what
makes parameter-server training viable on slow DCN links.

API surface matches the later-MXNet contract:
``kv.set_gradient_compression({"type": "2bit", "threshold": t})`` on a
dist kvstore; local stores reject it (same as the reference behavior).
"""

from __future__ import annotations

import numpy as np

__all__ = ["TwoBitCompressor", "OneBitCompressor", "compress_2bit",
           "decompress_2bit", "compress_1bit", "decompress_1bit"]

_WIRE_TAG = "__mxtpu_2bit__"
_WIRE_TAG_1BIT = "__mxtpu_1bit__"

# 2-bit codes: 00 = zero, 01 = +threshold, 10 = -threshold
_POS, _NEG = 1, 2


def compress_2bit(grad, threshold):
    """Quantize ``grad`` (any-shape f32) to packed 2-bit codes.

    Returns ``(payload, residual)`` where payload is the wire tuple
    ``(_WIRE_TAG, threshold, shape, packed_uint8)`` and residual is the
    quantization error (same shape as grad) for error feedback."""
    grad = np.asarray(grad, np.float32)
    flat = grad.reshape(-1)
    pos = flat >= threshold
    neg = flat <= -threshold
    codes = np.zeros(flat.shape, np.uint8)
    codes[pos] = _POS
    codes[neg] = _NEG
    # pack 4 codes per byte, little end first
    pad = (-len(codes)) % 4
    if pad:
        codes = np.concatenate([codes, np.zeros(pad, np.uint8)])
    quads = codes.reshape(-1, 4)
    packed = (quads[:, 0] | (quads[:, 1] << 2) | (quads[:, 2] << 4)
              | (quads[:, 3] << 6)).astype(np.uint8)
    deq = np.zeros(flat.shape, np.float32)
    deq[pos] = threshold
    deq[neg] = -threshold
    residual = (flat - deq).reshape(grad.shape)
    payload = (_WIRE_TAG, float(threshold), tuple(grad.shape), packed)
    return payload, residual


def decompress_2bit(payload):
    """Inverse of :func:`compress_2bit`: payload tuple -> f32 array."""
    tag, threshold, shape, packed = payload
    if tag != _WIRE_TAG:
        raise ValueError(f"not a 2bit payload (tag {tag!r})")
    n = int(np.prod(shape)) if shape else 1
    b = np.asarray(packed, np.uint8)
    codes = np.empty((len(b), 4), np.uint8)
    codes[:, 0] = b & 3
    codes[:, 1] = (b >> 2) & 3
    codes[:, 2] = (b >> 4) & 3
    codes[:, 3] = (b >> 6) & 3
    codes = codes.reshape(-1)[:n]
    out = np.zeros(n, np.float32)
    out[codes == _POS] = threshold
    out[codes == _NEG] = -threshold
    return out.reshape(shape)


def compress_1bit(grad):
    """1-bit sign compression (1-bit SGD, Seide et al. 2014): each
    element becomes sign(g) * s with ONE adaptive scale s = mean|g|
    per push — 32x smaller wire than f32.

    Returns ``(payload, residual)``; payload is
    ``(_WIRE_TAG_1BIT, scale, shape, packed_bits)``."""
    grad = np.asarray(grad, np.float32)
    flat = grad.reshape(-1)
    scale = float(np.mean(np.abs(flat))) if flat.size else 0.0
    pos = flat >= 0
    packed = np.packbits(pos)
    deq = np.where(pos, np.float32(scale), np.float32(-scale))
    residual = (flat - deq).reshape(grad.shape)
    payload = (_WIRE_TAG_1BIT, scale, tuple(grad.shape), packed)
    return payload, residual


def decompress_1bit(payload):
    tag, scale, shape, packed = payload
    if tag != _WIRE_TAG_1BIT:
        raise ValueError(f"not a 1bit payload (tag {tag!r})")
    n = int(np.prod(shape)) if shape else 1
    pos = np.unpackbits(np.asarray(packed, np.uint8))[:n].astype(bool)
    out = np.where(pos, np.float32(scale), np.float32(-scale))
    return out.reshape(shape)


def is_compressed(value) -> bool:
    return (isinstance(value, tuple) and len(value) == 4
            and value[0] in (_WIRE_TAG, _WIRE_TAG_1BIT))


def decompress(payload):
    """Dispatch on the wire tag (server side)."""
    if payload[0] == _WIRE_TAG:
        return decompress_2bit(payload)
    return decompress_1bit(payload)


class _ErrorFeedbackCompressor:
    """Shared per-key error-feedback flow: residual joins the next
    gradient, the codec hook quantizes, the new residual is stashed."""

    def __init__(self):
        self._residual = {}

    def _quantize(self, grad):
        raise NotImplementedError

    def compress(self, key, grad):
        grad = np.asarray(grad, np.float32)
        res = self._residual.get(key)
        if res is not None:
            grad = grad + res
        payload, residual = self._quantize(grad)
        self._residual[key] = residual
        return payload


class TwoBitCompressor(_ErrorFeedbackCompressor):
    """2-bit codec: {-threshold, 0, +threshold} per element."""

    def __init__(self, threshold=0.5):
        if threshold <= 0:
            raise ValueError("2bit threshold must be positive")
        super().__init__()
        self.threshold = float(threshold)

    def _quantize(self, grad):
        return compress_2bit(grad, self.threshold)


class OneBitCompressor(_ErrorFeedbackCompressor):
    """1-bit codec: sign * adaptive per-push scale."""

    def _quantize(self, grad):
        return compress_1bit(grad)


def make_compressor(params):
    """Factory for ``set_gradient_compression`` dicts (later-MXNet
    contract: {'type': '2bit', 'threshold': ...} or {'type': '1bit'})."""
    params = dict(params)
    kind = params.pop("type", None)
    if kind == "1bit":
        if params:
            raise ValueError(
                f"unknown 1bit option(s) {sorted(params)} (none supported)")
        return OneBitCompressor()
    if kind != "2bit":
        raise ValueError(f"unsupported gradient compression {kind!r} "
                         "(supported: '1bit', '2bit')")
    unknown = set(params) - {"threshold"}
    if unknown:
        raise ValueError(
            f"unknown gradient compression option(s) {sorted(unknown)} "
            "(supported: 'threshold')")
    return TwoBitCompressor(**params)
