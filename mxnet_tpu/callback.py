"""Training callbacks (rebuild of python/mxnet/callback.py)."""

from __future__ import annotations

import logging
import math
import time

__all__ = ["do_checkpoint", "module_checkpoint", "Speedometer", "ProgressBar",
           "log_train_metric", "BatchEndParam",
           "LogValidationMetricsCallback"]


class BatchEndParam:
    """Callback payload (callback.py namedtuple equivalent)."""

    def __init__(self, epoch, nbatch, eval_metric, locals=None):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = eval_metric
        self.locals = locals


def do_checkpoint(prefix, period=1, async_save=False):
    """Epoch-end checkpoint callback (callback.py:11-32).  With
    ``async_save`` the disk write happens on a background thread
    (model.save_checkpoint async contract) so epochs don't stall on
    storage."""
    from .model import save_checkpoint

    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux,
                            async_save=async_save)

    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)

    return _callback


def log_train_metric(period, auto_reset=False):
    """Log metric every ``period`` batches (callback.py:35-57)."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class Speedometer:
    """samples/sec logging (callback.py:61-103) — the reference's
    throughput harness, kept as the benchmark metric surface."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.last_speed = None

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                # perf_counter, not time.time(): an NTP step between
                # ticks would report garbage samples/sec (same fix as
                # the fit loop's epoch clock)
                speed = self.frequent * self.batch_size / (
                    time.perf_counter() - self.tic)
                self.last_speed = speed
                if param.eval_metric is not None:
                    for name, value in param.eval_metric.get_name_value():
                        logging.info(
                            "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\tTrain-%s=%f",
                            param.epoch, count, speed, name, value)
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.perf_counter()
        else:
            self.init = True
            self.tic = time.perf_counter()


class ProgressBar:
    """ASCII progress bar (callback.py ProgressBar)."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s", prog_bar, percents, "%")


class DeadNodeMonitor:
    """Surface kvstore dead-worker detection inside the training loop.

    The reference exposes failure detection only as a pollable
    ``kvstore.num_dead_node`` (kvstore.h:235-244 over ps-lite
    heartbeats); this callback closes the loop to the trainer: pass it
    as a ``batch_end_callback`` (or ``epoch_end_callback``) to
    ``Module.fit`` / ``FeedForward.fit`` and every ``period`` calls it
    queries ``kv.dead_nodes(timeout)``.  On detection it invokes
    ``on_dead(ranks)`` if given (e.g. trigger a checkpoint + clean exit
    so the launcher's elastic restart takes over), else raises
    ``RuntimeError`` naming the dead ranks — failing the job fast
    instead of hanging in the next sync round.
    """

    def __init__(self, kv, period=50, timeout=60.0, on_dead=None):
        self.kv = kv
        self.period = max(int(period), 1)
        self.timeout = timeout
        self.on_dead = on_dead
        self._count = 0

    def __call__(self, *args, **kwargs):
        # every callback slot has a different invocation signature
        # (BatchEndParam here, (epoch, symbol, arg, aux) in Module's
        # epoch-end, (epoch, trainer) in ShardedTrainer's) — the
        # monitor ignores the payload, so accept them all
        self._count += 1
        if self._count % self.period:
            return
        dead = self.kv.dead_nodes(self.timeout)
        if not dead:
            return
        if self.on_dead is not None:
            self.on_dead(dead)
            return
        raise RuntimeError(
            f"dead workers detected: ranks {dead} missed heartbeats for "
            f">{self.timeout}s (kvstore '{getattr(self.kv, 'type', '?')}')"
            " — failing fast; restart the job from the last checkpoint")


class LogValidationMetricsCallback:
    """Log eval metrics at epoch end (reference callback.py:127-136);
    pass as ``eval_end_callback`` to ``fit``."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f",
                         param.epoch, name, value)
