"""Graph executor.

Rebuild of the reference GraphExecutor (src/symbol/graph_executor.cc,
include/mxnet/symbolic.h:320-420, python frontend python/mxnet/executor.py)
for the XLA compilation model.

Design mapping (SURVEY.md §7):

- The reference plans memory, instantiates per-node operators, and pushes
  cached engine ops per node, fusing runs of ops into "bulk segments"
  (graph_executor.cc:842-892).  Here the *entire per-context subgraph* is
  one bulk segment: a single jitted XLA program.  XLA buffer assignment
  replaces GraphStorageAllocator; XLA fusion replaces the engine's
  op-level pipelining; JAX async dispatch preserves the asynchronous
  ``forward()``-returns-immediately semantics.
- ``MakeBackwardPass`` (static_graph.cc:396-550) — the explicit backward
  graph transform — becomes ``jax.vjp`` over the traced forward, with
  loss-layer custom backward rules applied through ``jax.custom_vjp`` and
  gradient checkpointing ("mirroring", MXNET_BACKWARD_DO_MIRROR) mapped
  to ``jax.checkpoint`` on nodes carrying the ``force_mirroring`` attr.
- ``grad_req`` add/write/null (OpReqType, operator.h:23-36) is applied
  when gradients are committed to the bound grad arrays; XLA input/output
  aliasing (buffer donation) replaces the reference's inplace planning.

Training-mode ``forward`` eagerly launches the fused forward+backward
program with default head gradients (ones): for loss-headed graphs this
is exactly one compiled train step — the TPU-idiomatic execution unit —
and ``backward()`` just commits the already-computed gradients.  Custom
head gradients fall back to re-running the fused program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import ndarray as nd
from . import random as _random
from .base import MXNetError, np_dtype
from .context import Context
from .ndarray import NDArray

__all__ = ["Executor"]


def _wrap_custom_vjp(op, params):
    """Wrap an op with explicit backward into jax.custom_vjp."""

    @jax.custom_vjp
    def f(*inputs):
        outs, _ = op.forward(params, list(inputs), [], True, None)
        return tuple(outs)

    def f_fwd(*inputs):
        outs = f(*inputs)
        return outs, (inputs, outs)

    def f_bwd(res, gouts):
        inputs, outs = res
        gins = op.backward(params, list(gouts), list(inputs), list(outs))
        return tuple(gins)

    f.defvjp(f_fwd, f_bwd)
    return f


class _CompiledGraph:
    """Traceable evaluator for a Symbol's node graph on one context."""

    def __init__(self, symbol):
        import os

        self.symbol = symbol
        self.topo = symbol._topo()
        # global gradient-checkpointing switch (reference
        # MXNET_BACKWARD_DO_MIRROR, static_graph.cc:396-440); per-node
        # force_mirroring attrs still apply when unset
        self._mirror_all = os.environ.get(
            "MXNET_BACKWARD_DO_MIRROR", "0") in ("1", "true", "True")
        self.heads = symbol._heads
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.rng_nodes = [n for n in self.topo
                          if not n.is_variable and n.op.need_rng]
        self._custom = {}
        self._aux_of_node = {}
        for node in self.topo:
            if node.is_variable:
                continue
            n_args = len(node.op.list_arguments(node.params))
            aux_vars = [src.name for src, _ in node.inputs[n_args:]]
            self._aux_of_node[id(node)] = (n_args, aux_vars)
            if node.op.has_backward:
                self._custom[id(node)] = _wrap_custom_vjp(node.op, node.params)
        self._segments = self._build_segments()

    def _node_mirrored(self, node):
        return self._mirror_all or node.attrs.get(
            "force_mirroring", "") in ("1", "true", "True")

    def _build_segments(self):
        """Group maximal CONTIGUOUS runs of mirrored compute nodes into
        block-level rematerialization segments: one ``jax.checkpoint``
        around the whole run saves only the block-boundary activations
        (reference mirroring marks per-layer boundaries the same way,
        static_graph.cc:396-440) — per-node checkpointing would still
        keep every inter-op activation alive.

        Returns a list of ('node', node) / ('remat', [nodes]) entries;
        only used on the train path (eval and monitor runs stay
        per-node)."""
        # variables carry no activations and have no inputs: placing
        # them all first preserves dataflow order and keeps them from
        # splitting mirrored runs (weights interleave compute in topo
        # order)
        segments = [("node", n) for n in self.topo if n.is_variable]
        run = []

        def flush():
            if len(run) > 1:
                segments.append(("remat", list(run)))
            else:
                segments.extend(("node", n) for n in run)
            run.clear()

        # only nodes carrying a ``mirror_stage`` attr group into blocks
        # (the reference's mirror-stage grouping); a stage change breaks
        # the run so each layer checkpoints independently rather than
        # the whole net collapsing into one full-recompute region.
        # Mirrored nodes WITHOUT a stage (e.g. the global
        # MXNET_BACKWARD_DO_MIRROR switch) keep per-node checkpointing.
        prev_stage = None
        for node in self.topo:
            if node.is_variable:
                continue
            stage = node.attrs.get("mirror_stage")
            if self._node_mirrored(node) and stage is not None:
                if run and stage != prev_stage:
                    flush()
                prev_stage = stage
                run.append(node)
            else:
                flush()
                prev_stage = None
                segments.append(("node", node))
        flush()

        # consumers outside each block + heads define the block outputs
        consumed = {}
        for node in self.topo:
            if node.is_variable:
                continue
            n_args, _ = self._aux_of_node[id(node)]
            for src, idx in node.inputs[:n_args]:
                consumed.setdefault((id(src), idx), set()).add(id(node))
        head_keys = {(id(n), i) for n, i in self.heads}

        out = []
        for kind, payload in segments:
            if kind != "remat":
                out.append((kind, payload))
                continue
            nodes = payload
            block_ids = {id(n) for n in nodes}
            ext_keys, seen = [], set()
            aux_in, aux_seen = [], set()
            for n in nodes:
                n_args, aux_names = self._aux_of_node[id(n)]
                for src, idx in n.inputs[:n_args]:
                    k = (id(src), idx)
                    if id(src) not in block_ids and k not in seen:
                        seen.add(k)
                        ext_keys.append(k)
                for a in aux_names:
                    if a not in aux_seen:
                        aux_seen.add(a)
                        aux_in.append(a)
            out_keys = []
            for n in nodes:
                for i in range(n.num_outputs()):
                    k = (id(n), i)
                    users = consumed.get(k, set())
                    if k in head_keys or users - block_ids:
                        out_keys.append(k)
            out.append(("remat", (nodes, ext_keys, aux_in, out_keys)))
        return out

    def _run_node(self, node, env, new_aux, subkeys, rng_idx, train,
                  collect, use_checkpoint=False):
        """Evaluate one node from/into env + new_aux."""
        n_args, aux_names = self._aux_of_node[id(node)]
        ins = [env[id(src), idx] for src, idx in node.inputs[:n_args]]
        auxs = [new_aux[a] for a in aux_names]
        if id(node) in self._custom:
            outs = list(self._custom[id(node)](*ins))
            node_new_aux = auxs
        else:
            nkey = (subkeys[rng_idx[id(node)]]
                    if id(node) in rng_idx else None)
            if use_checkpoint:
                pure = jax.checkpoint(
                    lambda *i, _n=node, _k=nkey, _a=auxs: _n.op.forward(
                        _n.params, list(i), list(_a), train, _k)[0])
                outs = list(pure(*ins))
                node_new_aux = node.op.forward(node.params, ins, auxs,
                                               train, nkey)[1]
            else:
                outs, node_new_aux = node.op.forward(node.params, ins, auxs,
                                                     train, nkey)
        for a, v in zip(aux_names, node_new_aux):
            new_aux[a] = v
        for i, o in enumerate(outs):
            # mxtpu-lint: disable=jit-cache-capture (env is the caller's
            # per-invocation value environment — traversal state over a
            # graph the executor owns, not a program cache)
            env[id(node), i] = o
            if collect is not None:
                out_name = (f"{node.name}_"
                            f"{node.op.list_outputs(node.params)[i]}")
                collect.append((out_name, o))

    def rng_state(self, key):
        """(subkeys, rng_idx) for one evaluation — THE key-splitting
        scheme.  Both the fused path (__call__) and the stepwise path
        (Executor.partial_forward) derive per-node keys through this one
        helper, so a stepwise run reproduces fused randomness exactly."""
        subkeys = (jax.random.split(key, len(self.rng_nodes))
                   if self.rng_nodes else None)
        rng_idx = {id(n): i for i, n in enumerate(self.rng_nodes)}
        return subkeys, rng_idx

    def __call__(self, arg_vals: dict, aux_vals: dict, key, train: bool,
                 collect=None):
        """Evaluate the graph.  JAX-traceable for fixed ``train``.

        Returns (outputs tuple, new_aux dict)."""
        env = {}
        subkeys, rng_idx = self.rng_state(key)
        new_aux = dict(aux_vals)
        # block-level remat applies on the train path only (backward is
        # what stores activations); monitor runs need every output, so
        # they also take the per-node path
        use_segments = train and collect is None

        def place_var(node):
            if node.name in arg_vals:
                env[id(node), 0] = arg_vals[node.name]
            elif node.name in aux_vals:
                env[id(node), 0] = aux_vals[node.name]

        if not use_segments:
            for node in self.topo:
                if node.is_variable:
                    place_var(node)
                    continue
                self._run_node(node, env, new_aux, subkeys, rng_idx, train,
                               collect,
                               use_checkpoint=train
                               and self._node_mirrored(node))
            outputs = tuple(env[id(n), i] for n, i in self.heads)
            return outputs, new_aux

        for kind, payload in self._segments:
            if kind == "node":
                node = payload
                if node.is_variable:
                    place_var(node)
                else:
                    self._run_node(node, env, new_aux, subkeys, rng_idx,
                                   train, None,
                                   use_checkpoint=self._node_mirrored(node))
                continue
            nodes, ext_keys, aux_in, out_keys = payload
            block_keys = [subkeys[rng_idx[id(n)]] for n in nodes
                          if id(n) in rng_idx]

            # _run_node's rng plumbing expects (subkeys, rng_idx); build
            # block-local versions so the checkpointed body stays simple
            def seg_fn(ext_vals, aux_vals_in, keys_in, _nodes=nodes,
                       _ext=ext_keys, _aux=aux_in, _out=out_keys):
                local_env = dict(zip(_ext, ext_vals))
                local_aux = dict(zip(_aux, aux_vals_in))
                rng_nodes = [n for n in _nodes if id(n) in rng_idx]
                local_idx = {id(n): i for i, n in enumerate(rng_nodes)}
                for n in _nodes:
                    self._run_node(n, local_env, local_aux, keys_in,
                                   local_idx, train, None)
                return (tuple(local_env[k] for k in _out),
                        tuple(local_aux[a] for a in _aux))

            wrapped = jax.checkpoint(seg_fn)
            ext_vals = tuple(env[k] for k in ext_keys)
            aux_vals_in = tuple(new_aux[a] for a in aux_in)
            out_vals, aux_out = wrapped(ext_vals, aux_vals_in,
                                        tuple(block_keys))
            for k, v in zip(out_keys, out_vals):
                env[k] = v
            for a, v in zip(aux_in, aux_out):
                new_aux[a] = v
        outputs = tuple(env[id(n), i] for n, i in self.heads)
        return outputs, new_aux


class Executor:
    """Bound, compiled computation (reference python/mxnet/executor.py).

    Single-context graphs compile to one fused XLA program; graphs whose
    nodes span contexts (``ctx_group`` attrs via ``group2ctx``, or bound
    arrays on different devices) execute as per-context compiled segments
    with automatic cross-device transfers (see mxnet_tpu.graph).
    """

    def __init__(self, symbol, ctx, grad_req, arg_arrays, grad_arrays, aux_arrays,
                 group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()
        self.arg_arrays = arg_arrays
        self.grad_arrays = grad_arrays
        self.aux_arrays = aux_arrays
        self.arg_dict = dict(zip(self.arg_names, arg_arrays))
        self.grad_dict = {k: g for k, g in zip(self.arg_names, grad_arrays)
                          if g is not None}
        self.aux_dict = dict(zip(self.aux_names, aux_arrays))
        if isinstance(grad_req, str):
            grad_req = {k: grad_req for k in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self.arg_names, grad_req))
        self._grad_req = {k: (grad_req.get(k, "null") if grad_arrays else "null")
                          for k in self.arg_names}
        for k, g in zip(self.arg_names, grad_arrays or [None] * len(self.arg_names)):
            if g is None:
                self._grad_req[k] = "null"
        self._grad_names = [k for k in self.arg_names if self._grad_req[k] != "null"]

        self._graph = _CompiledGraph(symbol)
        self._key = _random.next_key()
        self._outputs = None
        self._pending_grads = None
        self._monitor_callback = None
        # stepwise-execution state (partial_forward)
        self._fwd_nodes = [n for n in self._graph.topo if not n.is_variable]
        self._partial = None
        self._partial_key = None
        # key of the last executed forward (fused or stepwise): explicit
        # out_grads backward re-runs the fused program with it so RNG ops
        # (dropout) reproduce the activations the caller observed
        self._last_key = None

        # -- context assignment (model parallelism) -------------------------
        from .graph import SegmentedGraph, assign_contexts

        self._arg_ctx = {name: arr.context
                         for name, arr in zip(self.arg_names, arg_arrays)}
        var_ctx = dict(self._arg_ctx)
        for name, arr in zip(self.aux_names, aux_arrays):
            var_ctx[name] = arr.context
        ctx_of = assign_contexts(symbol, ctx, group2ctx, var_ctx)
        distinct = {c for c in ctx_of.values()}
        self._multi_ctx = len(distinct) > 1
        if self._multi_ctx:
            self._ctx_of = ctx_of
            self._seg_graph = SegmentedGraph(symbol, ctx_of,
                                             self._graph._custom)
            self._pending_chain = None
            self._head_ctx = []
            for node, idx in symbol._heads:
                if node.is_variable:
                    self._head_ctx.append(self._arg_ctx[node.name])
                else:
                    self._head_ctx.append(ctx_of[id(node)])
            return

        # --- compiled entry points (single-context fused path) ---
        graph = self._graph

        def fwd(train, args, aux, key):
            outs, new_aux = graph(args, aux, key, train)
            return outs, new_aux

        self._fwd_eval = jax.jit(lambda a, x, k: fwd(False, a, x, k))
        self._fwd_train = jax.jit(lambda a, x, k: fwd(True, a, x, k))

        def fwd_bwd(grad_args, other_args, aux, key, head_grads):
            def f(ga):
                outs, new_aux = graph({**ga, **other_args}, aux, key, True)
                return outs, new_aux

            outs, vjp_fn, new_aux = jax.vjp(f, grad_args, has_aux=True)
            grads = vjp_fn(head_grads)[0]
            return outs, grads, new_aux

        self._fwd_bwd = jax.jit(fwd_bwd)

    # -- factory helpers (Symbol.bind / simple_bind) -------------------------
    @staticmethod
    def _bind(symbol, ctx, args, args_grad, grad_req, aux_states, group2ctx=None,
              shared_exec=None):
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_arrays = Executor._to_list(args, arg_names, "args")
        if args_grad is None:
            grad_arrays = [None] * len(arg_names)
        else:
            grad_arrays = Executor._to_list(args_grad, arg_names, "args_grad",
                                            allow_missing=True)
        aux_arrays = Executor._to_list(aux_states or [], aux_names, "aux_states")
        return Executor(symbol, ctx, grad_req, arg_arrays, grad_arrays, aux_arrays,
                        group2ctx=group2ctx)

    @staticmethod
    def _simple_bind(symbol, ctx, grad_req="write", type_dict=None, group2ctx=None,
                     shared_exec=None, **kwargs):
        arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
        type_dict = type_dict or {}
        arg_types, _, aux_types = symbol.infer_type(**{
            k: v for k, v in type_dict.items()})
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()

        def _reuse(pool, name, shape, dtype, c):
            """Memory sharing with ``shared_exec`` (reference
            GraphStoragePool / executor_group._bind_ith_exec:439-533):
            reuse the shared executor's NDArray OBJECT when name, shape,
            dtype and context all match — both executors then see every
            update to it.  XLA buffer assignment owns the internal
            activation memory, so the array objects are the entire
            shareable surface here.  Inputs the caller gave shapes for
            (data/labels — the non-param arguments) are never shared:
            a deferred backward re-gathers its executor's inputs, and
            aliasing them across executors would let another module's
            batch leak into those gradients.  A name the donor holds at
            a DIFFERENT shape/dtype/context is an error, not a silent
            fresh allocation: partial sharing would leave that one
            parameter training independently while the master dicts stay
            shared (the reference's _bind_ith_exec asserts the same)."""
            if pool is None or name in kwargs:
                return None
            arr = pool.get(name)
            if arr is None:
                return None
            if (tuple(arr.shape) != tuple(shape)
                    or np.dtype(arr.dtype) != np.dtype(dtype or np.float32)
                    or arr.context != c):
                raise MXNetError(
                    f"shared_exec holds {name!r} with shape "
                    f"{tuple(arr.shape)} dtype {arr.dtype} on {arr.context}"
                    f", incompatible with required shape {tuple(shape)} "
                    f"dtype {np.dtype(dtype or np.float32)} on {c}")
            return arr

        shared_args = shared_exec.arg_dict if shared_exec is not None else None
        shared_grads = shared_exec.grad_dict if shared_exec is not None else None
        shared_aux = shared_exec.aux_dict if shared_exec is not None else None
        # with ctx groups, allocate each variable on its assigned context
        # (reference simple_bind honors AssignContext placements)
        if group2ctx:
            from .graph import assign_contexts

            ctx_of = assign_contexts(symbol, ctx, group2ctx)
            name_ctx = {}
            for node in symbol._topo():
                if node.is_variable:
                    name_ctx[node.name] = ctx_of[id(node)]
        else:
            name_ctx = {}
        arg_ctxs = [name_ctx.get(k, ctx) for k in arg_names]
        aux_ctxs = [name_ctx.get(k, ctx) for k in aux_names]
        def _alloc(pool, k, s, t, c):
            arr = _reuse(pool, k, s, t, c)
            if arr is None:
                arr = nd.zeros(s, ctx=c, dtype=t or np.float32)
            return arr

        arg_arrays = [_alloc(shared_args, k, s, t, c)
                      for k, s, t, c in zip(arg_names, arg_shapes, arg_types,
                                            arg_ctxs)]
        aux_arrays = [_alloc(shared_aux, k, s, t, c)
                      for k, s, t, c in zip(aux_names, aux_shapes, aux_types,
                                            aux_ctxs)]
        req = grad_req if isinstance(grad_req, dict) else {
            k: grad_req for k in arg_names}
        grad_arrays = [
            _alloc(shared_grads, k, s, t, c)
            if req.get(k, "null") != "null" else None
            for k, s, t, c in zip(arg_names, arg_shapes, arg_types, arg_ctxs)
        ]
        return Executor(symbol, ctx, req, arg_arrays, grad_arrays, aux_arrays,
                        group2ctx=group2ctx)

    @staticmethod
    def _to_list(values, names, what, allow_missing=False):
        if isinstance(values, dict):
            out = []
            for k in names:
                if k in values:
                    out.append(values[k])
                elif allow_missing:
                    out.append(None)
                else:
                    raise MXNetError(f"{what}: missing entry for {k!r}")
            return out
        values = list(values)
        if len(values) != len(names):
            raise MXNetError(f"{what}: expected {len(names)} entries, got {len(values)}")
        return values

    # -- execution ----------------------------------------------------------
    def _gather(self):
        args = {k: a._data for k, a in zip(self.arg_names, self.arg_arrays)}
        aux = {k: a._data for k, a in zip(self.aux_names, self.aux_arrays)}
        return args, aux

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        self._last_key = sub
        return sub

    def _run_fused_bwd(self, key, head=None):
        """Fused forward+backward over the CURRENT arrays with ``key``.
        ``head=None`` means ones per output — the loss-layer head-grad
        contract.  Single source for the deferred-grad, explicit
        out_grads, and completed-stepwise backward paths."""
        args, aux = self._gather()
        grad_args = {k: args[k] for k in self._grad_names}
        other = {k: v for k, v in args.items() if k not in grad_args}
        if head is None:
            outs_probe = jax.eval_shape(
                lambda a, x, k: self._fwd_train(a, x, k)[0], args, aux, key)
            head = tuple(jnp.ones(o.shape, o.dtype) for o in outs_probe)
        from .optimizer import _dispatch_inc

        _dispatch_inc(self, "fwd_bwd")
        return self._fwd_bwd(grad_args, other, aux, key, head)

    def forward(self, is_train=False, **kwargs):
        """Run forward (reference executor.py:84).  kwargs assign input
        arrays by name before running (e.g. ``exe.forward(data=batch)``)."""
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError(f"unknown input {k!r}")
            if isinstance(v, NDArray):
                self.arg_dict[k][:] = v
            else:
                self.arg_dict[k][:] = nd.array(v, ctx=self._ctx)
        args, aux = self._gather()
        key = self._next_key()
        # a fresh full forward invalidates any stepwise run in flight
        self._partial = None
        self._partial_key = None

        if self._multi_ctx:
            build_vjp = bool(is_train and self._grad_names)
            head_outs, new_aux, chain = self._seg_graph.forward(
                args, self._arg_ctx, aux, key, is_train, build_vjp)
            self._pending_chain = chain
            if is_train:
                for k, arr in zip(self.aux_names, self.aux_arrays):
                    arr._set(jax.device_put(new_aux[k],
                                            arr._ctx.jax_device()))
            self._outputs = [NDArray(o, c)
                             for o, c in zip(head_outs, self._head_ctx)]
            return self._outputs

        if self._monitor_callback is not None:
            collect = []
            outs, new_aux = self._graph(args, aux, key, is_train, collect=collect)
            for name, val in collect:
                self._monitor_callback(name, NDArray(val, self._ctx))
            if is_train and self._grad_names:
                # monitoring runs the graph eagerly for the per-output
                # stats; gradients come from the fused program with the
                # SAME key (identical activations), so backward() after a
                # monitored train step works exactly like an unmonitored
                # one — the reference Monitor is a training-loop tool
                _, grads, _ = self._run_fused_bwd(key)
                self._pending_grads = grads
            else:
                # no gradients for THIS run; a stale pending set from an
                # earlier fused train step must not survive it
                self._pending_grads = None
        elif is_train and self._grad_names:
            outs, grads, new_aux = self._run_fused_bwd(key)
            self._pending_grads = grads
        else:
            fn = self._fwd_train if is_train else self._fwd_eval
            outs, new_aux = fn(args, aux, key)
            self._pending_grads = None

        if is_train:
            for k, arr in zip(self.aux_names, self.aux_arrays):
                arr._set(new_aux[k])
        self._outputs = [NDArray(o, self._ctx) for o in outs]
        return self._outputs

    @property
    def num_forward_nodes(self):
        """Number of forward compute nodes = number of partial_forward
        steps (reference GraphExecutor num_forward_nodes_)."""
        return len(self._fwd_nodes)

    def partial_forward(self, is_train=False, step=0):
        """Run forward node ``step`` only and return the number of steps
        left (reference ``GraphExecutor::PartialForward``,
        src/symbol/graph_executor.cc:994-1001; contract in
        include/mxnet/symbolic.h:326-340: keep calling with increasing
        ``step`` until 0 is returned).

        This is the stepwise debugging path: each node executes eagerly
        (un-fused, like the reference disabling bulk exec), firing the
        monitor callback per output when one is installed.  After the
        final step, ``outputs`` matches a full ``forward`` run bit-for-bit
        and — on the single-context path — ``backward()`` works with the
        same key-reuse semantics as the fused train step.
        """
        if step >= len(self._fwd_nodes):
            return 0
        st = self._partial
        if step == 0:
            # starting a stepwise run invalidates any earlier fused run's
            # pending gradients/chain — they describe other activations
            self._pending_grads = None
            self._partial_key = None
            if self._multi_ctx:
                self._pending_chain = None
            args, aux = self._gather()
            key = self._next_key()
            env = {}
            for node in self._graph.topo:
                if node.is_variable:
                    if node.name in args:
                        env[id(node), 0] = args[node.name]
                    elif node.name in aux:
                        env[id(node), 0] = aux[node.name]
            subkeys, rng_idx = self._graph.rng_state(key)
            st = self._partial = {
                "env": env,
                "aux": dict(aux),
                "subkeys": subkeys,
                "rng_idx": rng_idx,
                "key": key,
                "next": 0,
            }
        if st is None or step != st["next"]:
            expected = 0 if st is None else st["next"]
            raise MXNetError(
                f"partial_forward step {step}: steps must be executed in "
                f"increasing order from 0 (expected step {expected})")
        env = st["env"]
        node = self._fwd_nodes[step]
        n_args, _ = self._graph._aux_of_node[id(node)]
        if self._multi_ctx:
            # honor the node's assigned context (model parallelism): move
            # its inputs like the auto-inserted _CrossDeviceCopy nodes
            dev = self._ctx_of[id(node)].jax_device()
            for src, idx in node.inputs[:n_args]:
                env[id(src), idx] = jax.device_put(env[id(src), idx], dev)
        collect = [] if self._monitor_callback is not None else None
        self._graph._run_node(node, env, st["aux"], st["subkeys"],
                              st["rng_idx"], is_train, collect)
        st["next"] = step + 1
        if collect:
            out_ctx = (self._ctx_of[id(node)] if self._multi_ctx
                       else self._ctx)
            for name, val in collect:
                self._monitor_callback(name, NDArray(val, out_ctx))
        step_left = len(self._fwd_nodes) - step - 1
        if step_left == 0:
            outs = tuple(env[id(n), i] for n, i in self._graph.heads)
            ctxs = (self._head_ctx if self._multi_ctx
                    else [self._ctx] * len(outs))
            self._outputs = [NDArray(o, c) for o, c in zip(outs, ctxs)]
            if is_train:
                for k, arr in zip(self.aux_names, self.aux_arrays):
                    arr._set(jax.device_put(st["aux"][k],
                                            arr._ctx.jax_device()))
                if not self._multi_ctx:
                    # backward() without out_grads re-runs the fused
                    # program with this key, reproducing the stepwise
                    # run's randomness exactly
                    self._partial_key = st["key"]
            self._pending_grads = None
            if self._multi_ctx:
                # a chain from an earlier fused forward would describe
                # stale activations; backward after a stepwise multi-ctx
                # run requires explicit out_grads through a fresh forward
                self._pending_chain = None
            self._partial = None
        return step_left

    def backward(self, out_grads=None):
        """Commit gradients (reference executor.py:123).

        With no ``out_grads``: gradients from the fused train step (head
        gradients = ones, the loss-layer contract) are committed.  With
        explicit head gradients the fused program re-runs with them.
        """
        if not self._grad_names:
            return
        if self._multi_ctx:
            if self._pending_chain is None:
                raise MXNetError("backward called before forward(is_train=True)")
            if out_grads is None:
                head_grads = [jnp.ones(o.shape, o.dtype) for o in self._outputs]
            else:
                if isinstance(out_grads, NDArray):
                    out_grads = [out_grads]
                head_grads = [g._data if isinstance(g, NDArray)
                              else jnp.asarray(g) for g in out_grads]
            grads = self._seg_graph.backward(self._pending_chain, head_grads,
                                             self._arg_ctx, self._grad_names)
            for k, garr in zip(self.arg_names, self.grad_arrays):
                if garr is None or self._grad_req[k] == "null":
                    continue
                g = grads[k]
                if g is None:
                    continue
                g = jax.device_put(g, garr._ctx.jax_device())
                garr._set(garr._data + g if self._grad_req[k] == "add" else g)
            self._pending_chain = None
            return
        if out_grads is not None:
            if self._last_key is None:
                raise MXNetError("backward called before forward")
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            # copy head grads to this executor's device (the reference
            # Backward copies/verifies head grads, graph_executor.cc:1003
            # — callers routinely pass default-context arrays); re-run
            # with the LAST forward's key so RNG ops reproduce the
            # activations the caller observed
            dev = self._ctx.jax_device()
            head = tuple(jax.device_put(
                g._data if isinstance(g, NDArray) else jnp.asarray(g), dev)
                for g in out_grads)
            _, grads, _ = self._run_fused_bwd(self._last_key, head)
        elif self._pending_grads is not None:
            grads = self._pending_grads
        elif self._partial_key is not None:
            # completed stepwise train run: compute grads by re-running
            # the fused program with the SAME key the partial run used
            # (identical randomness => identical activations)
            _, grads, _ = self._run_fused_bwd(self._partial_key)
        else:
            raise MXNetError("backward called before forward(is_train=True)")
        for k, garr in zip(self.arg_names, self.grad_arrays):
            if garr is None or self._grad_req[k] == "null":
                continue
            g = grads[k]
            if self._grad_req[k] == "add":
                garr._set(garr._data + g)
            else:
                garr._set(g)
        self._pending_grads = None
        self._partial_key = None

    @property
    def outputs(self):
        if self._outputs is None:
            raise MXNetError("run forward() first")
        return self._outputs

    @property
    def output_dict(self):
        """Name -> output NDArray (reference executor.py:215-233; raises
        on duplicated output names like the reference)."""
        outs = self.outputs
        d = {}
        for name, arr in zip(self.output_names, outs):
            if name in d:
                raise MXNetError(
                    f"duplicate output name {name!r}: use `outputs` for "
                    "positional access")
            d[name] = arr
        return d

    # -- misc API -----------------------------------------------------------
    def set_monitor_callback(self, callback):
        """Install per-output stat callback; switches to eager (un-fused)
        execution like the reference disabling bulk exec under monitor
        (graph_executor.cc:904)."""
        self._monitor_callback = callback

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for k, v in arg_params.items():
            if k in self.arg_dict:
                self.arg_dict[k][:] = v
            elif not allow_extra_params:
                raise MXNetError(f"unknown arg {k!r}")
        if aux_params:
            for k, v in aux_params.items():
                if k in self.aux_dict:
                    self.aux_dict[k][:] = v
                elif not allow_extra_params:
                    raise MXNetError(f"unknown aux {k!r}")

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes (reference
        python/mxnet/executor.py reshape semantics):

        - an array NOT named in ``kwargs`` may only change shape when
          ``partial_shaping=True``;
        - an array may only GROW when ``allow_up_sizing=True`` (the
          reference reuses the old buffer for same-or-smaller shapes).
        """
        import numpy as _np

        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        ctx = self._ctx

        def remake(name, arr, shp, direct):
            shp = tuple(shp)
            if tuple(arr.shape) == shp:
                return arr
            if not partial_shaping and not direct:
                raise MXNetError(
                    f"cannot reshape array {name!r}: its shape changed as a "
                    "consequence of the requested input shapes; pass "
                    "partial_shaping=True to allow this")
            if _np.prod(shp) > _np.prod(arr.shape):
                if not allow_up_sizing:
                    raise MXNetError(
                        f"new shape of arg {name!r} is larger than the "
                        "original; set allow_up_sizing=True to allocate a "
                        "bigger array")
                return nd.zeros(shp, ctx=ctx, dtype=arr.dtype)
            # same-or-smaller: the reference REUSES the old buffer
            # (arr.reshape over its leading elements); XLA arrays are
            # immutable, so carry the data by copying the flat prefix
            flat = arr._data.reshape(-1)[: int(_np.prod(shp))]
            return nd.NDArray(flat.reshape(shp), ctx)

        new_args, grad_arrays = [], []
        for name, shp, arr, garr in zip(self.arg_names, arg_shapes,
                                        self.arg_arrays, self.grad_arrays):
            new_args.append(remake(name, arr, shp, name in kwargs))
            grad_arrays.append(None if garr is None
                               else remake(name, garr, shp, name in kwargs))
        new_aux = [remake(name, arr, shp, False)
                   for name, shp, arr in zip(self._symbol.list_auxiliary_states(),
                                             aux_shapes, self.aux_arrays)]
        return Executor(self._symbol, ctx, self._grad_req, new_args, grad_arrays,
                        new_aux)

    def debug_str(self):
        return self._symbol.debug_str()
