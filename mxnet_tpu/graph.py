"""Multi-context graph partitioning and segmented execution.

Rebuild of the reference's model-parallelism machinery
(``AssignContext`` + auto-inserted ``_CrossDeviceCopy`` nodes,
src/symbol/graph_executor.cc:391-508; showcased by
example/model-parallel-lstm and tested by
tests/python/unittest/test_model_parallel.py):

- ``assign_contexts`` maps every node to a Context: explicit ``ctx_group``
  attrs resolved through ``group2ctx``, bound-array placements for
  variables, then forward/backward propagation along edges, defaulting to
  the bind context — the same precedence as the reference.
- ``SegmentedGraph`` splits the topo order into maximal same-context runs;
  each segment compiles to one jitted XLA program on its device (the
  per-context "bulk segment"), and boundary values move between chips as
  device-to-device transfers (ICI on TPU) — the copy-node equivalent.
  Backward chains per-segment ``jax.vjp``s in reverse with cotangent
  transfers, reproducing the reference's cross-device backward pass.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .context import Context

__all__ = ["assign_contexts", "SegmentedGraph"]


def assign_contexts(symbol, default_ctx, group2ctx=None, var_ctx=None):
    """Per-node Context assignment (graph_executor.cc:391-508 precedence).

    Returns dict id(node) -> Context.
    """
    topo = symbol._topo()
    group2ctx = group2ctx or {}
    var_ctx = var_ctx or {}
    ctx_of = {}
    for node in topo:
        grp = node.attrs.get("ctx_group")
        if grp and grp in group2ctx:
            ctx_of[id(node)] = group2ctx[grp]
        elif node.is_variable and node.name in var_ctx:
            ctx_of[id(node)] = var_ctx[node.name]
    changed = True
    while changed:
        changed = False
        # forward: inherit first known input context
        for node in topo:
            if id(node) in ctx_of:
                continue
            for src, _ in node.inputs:
                if id(src) in ctx_of:
                    ctx_of[id(node)] = ctx_of[id(src)]
                    changed = True
                    break
        # backward: producers inherit consumer context
        for node in reversed(topo):
            if id(node) not in ctx_of:
                continue
            for src, _ in node.inputs:
                if id(src) not in ctx_of:
                    ctx_of[id(src)] = ctx_of[id(node)]
                    changed = True
    for node in topo:
        ctx_of.setdefault(id(node), default_ctx)
    return ctx_of


class _Segment:
    __slots__ = ("nodes", "ctx", "in_keys", "out_keys", "aux_names",
                 "rng_nodes", "fn", "jit_train", "jit_eval")

    def __init__(self, ctx):
        self.ctx = ctx
        self.nodes = []
        self.in_keys = []
        self.out_keys = []
        self.aux_names = []
        self.rng_nodes = []
        self.fn = None
        self.jit_train = None
        self.jit_eval = None


class SegmentedGraph:
    """Executes a Symbol partitioned across contexts.

    Value keys: ("arg", name) for variable inputs (args and aux),
    ("out", id(node), i) for op outputs.  Each segment is a pure function
    (inputs, aux, key, train) -> (outputs, new_aux), jitted on its device.
    """

    def __init__(self, symbol, ctx_of, custom_vjp_of):
        self.symbol = symbol
        self.topo = symbol._topo()
        self.heads = symbol._heads
        self.aux_names = set(symbol.list_auxiliary_states())
        self._custom = custom_vjp_of
        self.ctx_of = ctx_of

        # split topo into maximal same-context runs of op nodes
        self.segments = []
        cur = None
        node_seg = {}
        for node in self.topo:
            if node.is_variable:
                continue
            ctx = ctx_of[id(node)]
            if cur is None or cur.ctx != ctx:
                cur = _Segment(ctx)
                self.segments.append(cur)
            cur.nodes.append(node)
            node_seg[id(node)] = cur
            if node.op.need_rng:
                cur.rng_nodes.append(node)

        # per-segment io sets
        head_keys = set()
        for node, i in self.heads:
            if node.is_variable:
                head_keys.add(("arg", node.name))
            else:
                head_keys.add(("out", id(node), i))
        consumed_later = {}  # key -> first consuming segment index
        for seg_idx, seg in enumerate(self.segments):
            in_set, produced = [], set()
            for node in seg.nodes:
                n_args = len(node.op.list_arguments(node.params))
                for src, idx in node.inputs[:n_args]:
                    key = (("arg", src.name) if src.is_variable
                           else ("out", id(src), idx))
                    if key not in produced and key not in in_set:
                        if src.is_variable or node_seg[id(src)] is not seg:
                            in_set.append(key)
                for aux_src, _ in node.inputs[n_args:]:
                    if aux_src.name not in seg.aux_names:
                        seg.aux_names.append(aux_src.name)
                for i in range(node.num_outputs()):
                    produced.add(("out", id(node), i))
            seg.in_keys = in_set
            seg.out_keys = []  # filled below once consumers are known
        # determine outputs: values produced in a segment and needed by a
        # later segment or by the heads
        producer = {}
        for seg in self.segments:
            for node in seg.nodes:
                for i in range(node.num_outputs()):
                    producer[("out", id(node), i)] = seg
        needed = set(head_keys)
        for seg in self.segments:
            for key in seg.in_keys:
                if key[0] == "out":
                    needed.add(key)
        for seg in self.segments:
            seg.out_keys = [k for k in needed
                            if k[0] == "out" and producer.get(k) is seg]
        self.producer = producer
        self._build_fns()

    # ------------------------------------------------------------------ #
    def _build_fns(self):
        for seg in self.segments:
            seg.fn = self._make_segment_fn(seg)
            seg.jit_train = jax.jit(lambda ins, aux, key, _f=seg.fn:
                                    _f(ins, aux, key, True))
            seg.jit_eval = jax.jit(lambda ins, aux, key, _f=seg.fn:
                                   _f(ins, aux, key, False))

    def _make_segment_fn(self, seg):
        in_keys = list(seg.in_keys)
        out_keys = list(seg.out_keys)
        custom = self._custom

        def fn(ins, aux_vals, key, train):
            env = dict(zip(in_keys, ins))
            new_aux = dict(aux_vals)
            subkeys = (jax.random.split(key, len(seg.rng_nodes))
                       if seg.rng_nodes else None)
            rng_idx = {id(n): i for i, n in enumerate(seg.rng_nodes)}
            for node in seg.nodes:
                n_args = len(node.op.list_arguments(node.params))
                ins_vals = []
                for src, idx in node.inputs[:n_args]:
                    k = (("arg", src.name) if src.is_variable
                         else ("out", id(src), idx))
                    ins_vals.append(env[k])
                auxs = [new_aux[s.name] for s, _ in node.inputs[n_args:]]
                if id(node) in custom:
                    outs = list(custom[id(node)](*ins_vals))
                    node_new_aux = auxs
                else:
                    nkey = (subkeys[rng_idx[id(node)]]
                            if id(node) in rng_idx else None)
                    outs, node_new_aux = node.op.forward(
                        node.params, ins_vals, auxs, train, nkey)
                for (s, _), v in zip(node.inputs[n_args:], node_new_aux):
                    new_aux[s.name] = v
                for i, o in enumerate(outs):
                    env[("out", id(node), i)] = o
            return tuple(env[k] for k in out_keys), new_aux

        return fn

    # ------------------------------------------------------------------ #
    def forward(self, arg_vals, arg_ctx, aux_vals, key, train, build_vjp):
        """Run all segments.  Returns (head_outputs, new_aux, vjp_chain).

        arg_vals: name -> jnp array (already on its context)
        aux_vals: name -> jnp array
        """
        env = {("arg", name): v for name, v in arg_vals.items()}
        aux_state = dict(aux_vals)
        vjp_chain = [] if build_vjp else None
        keys = jax.random.split(key, len(self.segments) + 1)
        for i, seg in enumerate(self.segments):
            dev = seg.ctx.jax_device()
            ins = tuple(jax.device_put(env[k], dev) for k in seg.in_keys)
            seg_aux = {n: jax.device_put(aux_state[n], dev)
                       for n in seg.aux_names}
            if build_vjp:
                outs, vjp_fn, new_aux = jax.vjp(
                    lambda _ins, _s=seg, _a=seg_aux, _k=keys[i]:
                    _s.jit_train(_ins, _a, _k), ins, has_aux=True)
                vjp_chain.append((seg, vjp_fn, [jnp.zeros(o.shape, o.dtype)
                                                for o in outs]))
            else:
                fn = seg.jit_train if train else seg.jit_eval
                outs, new_aux = fn(ins, seg_aux, keys[i])
            for k, v in zip(seg.out_keys, outs):
                env[k] = v
            aux_state.update(new_aux)
        head_outs = []
        for node, idx in self.heads:
            k = (("arg", node.name) if node.is_variable
                 else ("out", id(node), idx))
            head_outs.append(env[k])
        return head_outs, aux_state, vjp_chain

    def backward(self, vjp_chain, head_grads, arg_ctx, grad_names):
        """Chain per-segment vjps in reverse; returns name -> cotangent."""
        cot = {}

        def _acc(key, val, dev):
            val = jax.device_put(val, dev)
            if key in cot:
                cot[key] = cot[key] + val
            else:
                cot[key] = val

        for (node, idx), g in zip(self.heads, head_grads):
            if node.is_variable:
                key = ("arg", node.name)
                dev = arg_ctx[node.name].jax_device()
            else:
                key = ("out", id(node), idx)
                dev = self.producer[key].ctx.jax_device()
            _acc(key, g, dev)

        for seg, vjp_fn, zero_outs in reversed(vjp_chain):
            dev = seg.ctx.jax_device()
            out_cots = []
            for k, z in zip(seg.out_keys, zero_outs):
                if k in cot:
                    out_cots.append(jax.device_put(cot[k], dev))
                else:
                    out_cots.append(z)  # unused output: zero cotangent
            (in_cots,) = vjp_fn(tuple(out_cots))
            for k, g in zip(seg.in_keys, in_cots):
                if g is None or g.dtype == jax.dtypes.float0:
                    continue
                if k[0] == "arg":
                    dev_k = arg_ctx[k[1]].jax_device()
                else:
                    dev_k = self.producer[k].ctx.jax_device()
                _acc(k, g, dev_k)
        return {name: cot.get(("arg", name)) for name in grad_names}
