"""SFrame data-iterator bridge (rebuild of plugin/sframe).

The reference plugin builds against Turi/GraphLab's C++ SFrame to feed
``SFrameIter``/``SFrameImageIter`` from on-disk columnar frames.  Here
the iterator is duck-typed over any columnar frame object — a
``turicreate.SFrame``, a ``pandas.DataFrame``, or anything exposing
``frame[column]`` as an iterable of rows — and materializes the selected
columns to numpy, then batches through the NDArrayIter machinery
(host-side collation; device transfer happens at ``load_data_batch``
like every other iterator).
"""

from __future__ import annotations

import numpy as np

from .base import MXNetError
from .io import NDArrayIter

__all__ = ["SFrameIter", "SFrameImageIter"]


def _column(frame, name):
    try:
        col = frame[name]
    except (KeyError, TypeError) as e:
        raise MXNetError(f"SFrameIter: frame has no column {name!r}") from e
    rows = [np.asarray(r, dtype=np.float32) for r in col]
    if not rows:
        raise MXNetError(f"SFrameIter: column {name!r} is empty")
    first = rows[0].shape
    if any(r.shape != first for r in rows):
        raise MXNetError(
            f"SFrameIter: column {name!r} rows have inconsistent shapes "
            "(pack images to a fixed shape first)")
    return np.stack(rows) if first else np.asarray(rows, np.float32)


class SFrameIter(NDArrayIter):
    """Iterate a columnar frame (plugin/sframe SFrameIter analog).

    data_field: column name or list of names — multiple numeric columns
    are concatenated feature-wise, array-typed columns keep their shape.
    """

    def __init__(self, sframe, data_field, label_field=None, batch_size=1,
                 data_name="data", label_name="softmax_label", **kwargs):
        fields = ([data_field] if isinstance(data_field, str)
                  else list(data_field))
        cols = [_column(sframe, f) for f in fields]
        if len(cols) == 1:
            data = cols[0]
        else:
            flat = [c.reshape(len(c), -1) for c in cols]
            n = {len(c) for c in flat}
            if len(n) != 1:
                raise MXNetError("SFrameIter: columns differ in length")
            data = np.concatenate(flat, axis=1)
        label = _column(sframe, label_field) if label_field else None
        super().__init__(data, label, batch_size=batch_size,
                         data_name=data_name, label_name=label_name,
                         **kwargs)


class SFrameImageIter(SFrameIter):
    """Image variant (plugin/sframe SFrameImageIter): the image column
    holds fixed-shape arrays (H, W, C) or (C, H, W); optional float mean
    and scale are applied on the host like the reference's
    mean_r/g/b + scale params."""

    def __init__(self, sframe, data_field, label_field=None, batch_size=1,
                 mean=None, scale=1.0, **kwargs):
        super().__init__(sframe, data_field, label_field, batch_size,
                         **kwargs)
        arr = self.data[0][1]
        if arr.ndim != 4:
            raise MXNetError("SFrameImageIter: image column must hold "
                             f"fixed-shape 3d arrays, got {arr.shape[1:]}")
        out = arr.astype(np.float32)
        if mean is not None:
            out = out - np.asarray(mean, np.float32)
        if scale != 1.0:
            out = out * float(scale)
        self.data[0] = (self.data[0][0], out)
