"""GPT-style decoder-only transformer language model.

Beyond-parity model-zoo entry (the 2016 reference predates
transformers): pre-LayerNorm causal self-attention blocks over the
fused attention op (Pallas flash kernel on TPU), GELU MLPs, learned
positional embeddings.  Built from
registered symbol ops, so the whole Module/Executor/checkpoint stack
applies unchanged; sequence-parallel training of the same computation
lives in ``parallel/ring_attention.py`` / ``parallel/ulysses.py``.
"""

from __future__ import annotations

import contextlib

from .. import symbol as sym


def gpt(vocab_size, seq_len, num_layers=2, d_model=128, num_heads=4,
        d_ff=None, dropout=0.0, causal=True, remat=False, fused_qkv=False,
        attn_layout="bhsd", attn_impl="auto", attn_sp_impl="ring",
        kv_heads=None, attn_window=0, pos_embed="learned", loss="softmax",
        mlp="gelu", tie_embeddings=False, norm="layernorm", name="gpt"):
    """Symbol computing next-token softmax loss.

    Inputs: ``data`` (batch, seq_len) token ids; ``softmax_label``
    (batch, seq_len) next-token targets.  Output: per-position softmax
    (batch*seq_len, vocab_size).

    ``remat=True`` marks every transformer block ``force_mirroring`` so
    the executor rematerializes its activations in backward
    (jax.checkpoint) — activation memory drops from O(layers x seq) to
    O(seq) at ~1/3 extra FLOPs, the standard long-context trade.

    ``fused_qkv=True`` computes Q/K/V as ONE (d_model, 3*d_model) matmul
    per layer instead of three: the MXU does the same FLOPs but the
    activation tile is read from HBM once, and one weight layout stays
    resident.  Changes the checkpoint layout (``*_qkv_weight`` replaces
    ``*_{q,k,v}_weight``), so it is opt-in.

    ``attn_layout="bshd"`` keeps activations sequence-major through
    attention (kernel indexes the head dim; no BSHD<->BHSD transposes —
    the only activation transposes in the step's HLO).  Same math and
    checkpoint layout; opt-in pending on-chip measurement
    (BENCH_ATTN_LAYOUT sweep point).

    ``attn_impl``: "auto" uses the fused Pallas kernel on TPU —
    including under a multi-device data-parallel ShardedTrainer, where
    the op shard_maps the kernel over the batch axis (Mosaic custom
    calls cannot be GSPMD-auto-partitioned; ops/attention.py
    spmd_attention supplies the mesh).  "xla" forces the dense
    formulation.

    ``attn_sp_impl``: the schedule used when a ShardedTrainer shards
    the sequence axis (sequence_specs) — "ring" (ppermuted K/V shards;
    any head count) or "ulysses" (two all-to-alls re-shard seq<->heads;
    needs num_heads % sp == 0).

    ``loss``: "softmax" (reference SoftmaxOutput — per-position
    probabilities as the output) or "ce" (fused SoftmaxCELoss — the
    output is the (B*S,) per-position NLL; skips materializing the
    (B*S, vocab) probability tensor, gigabytes of HBM at transformer
    vocabularies).

    ``norm``: "layernorm" (GPT-2-style) or "rmsnorm" (llama-style —
    no mean subtraction or shift; ``*_gamma`` only in the checkpoint).

    ``mlp``: "gelu" (GPT-2-style up/GELU/down) or "swiglu"
    (llama-style gated MLP: silu(gate) * up -> down; pass a ~2/3-scaled
    ``d_ff`` to hold parameter count).  ``tie_embeddings=True`` shares
    the token-embedding matrix with the LM head (same named variable —
    the executor accumulates both gradient paths; no separate
    ``*_head_weight`` in the checkpoint).

    ``pos_embed``: "learned" (reference-style additive table) or
    "rope" (rotary embeddings applied to Q/K per layer — relative
    positions, the long-context standard; no position table in the
    checkpoint).

    ``kv_heads`` < num_heads is grouped-query/multi-query attention:
    the K/V projections shrink to kv_heads * head_dim and each group of
    q heads shares one K/V head (native in the Pallas kernel under
    attn_layout="bshd").  ``attn_window`` > 0 adds sliding-window
    locality (Mistral-class local attention).
    """
    if d_model % num_heads:
        raise ValueError("d_model must divide into num_heads")
    d_ff = d_ff or 4 * d_model
    head_dim = d_model // num_heads
    kv_heads = kv_heads or num_heads
    if num_heads % kv_heads:
        raise ValueError("num_heads must be a multiple of kv_heads")
    # GQA composes with fused_qkv: the fused projection emits
    # (d_model + 2*d_kv) columns and the slice bounds below use d_kv
    d_kv = kv_heads * head_dim

    def layer_scope(i):
        # mirror_stage separates per-layer checkpoint blocks: without it
        # consecutive mirrored layers would merge into one region whose
        # backward recomputes the entire stack
        if remat:
            return sym.AttrScope(force_mirroring="1", mirror_stage=str(i))
        return contextlib.nullcontext()

    if pos_embed not in ("learned", "rope"):
        raise ValueError(f"pos_embed must be learned|rope, got {pos_embed}")
    if loss not in ("softmax", "ce"):
        raise ValueError(f"loss must be softmax|ce, got {loss}")
    if mlp not in ("gelu", "swiglu"):
        raise ValueError(f"mlp must be gelu|swiglu, got {mlp}")
    if norm not in ("layernorm", "rmsnorm"):
        raise ValueError(f"norm must be layernorm|rmsnorm, got {norm}")

    def norm_layer(x, nm):
        if norm == "rmsnorm":
            return sym.RMSNorm(x, name=nm)
        return sym.LayerNorm(x, name=nm)
    if pos_embed == "rope" and head_dim % 2:
        raise ValueError("rope needs an even head_dim")
    data = sym.Variable("data")
    tok = sym.Embedding(data, name=f"{name}_tok_embed", input_dim=vocab_size,
                        output_dim=d_model)                  # (B, S, D)
    if pos_embed == "learned":
        pos = sym.Variable(f"{name}_pos_embed_weight",
                           shape=(1, seq_len, d_model))
        h = sym.broadcast_plus(tok, pos)
    else:
        h = tok              # rope: positions enter at each Q/K rotation

    for i in range(num_layers):
        p = f"{name}_l{i}"
        with layer_scope(i):
            # -- attention block (pre-LN) -------------------------------
            ln1 = norm_layer(h, f"{p}_ln1")
            flat = sym.Reshape(ln1, shape=(-1, d_model))
            if fused_qkv:
                qkv = sym.FullyConnected(flat, name=f"{p}_qkv",
                                         num_hidden=d_model + 2 * d_kv)
                q = sym.slice_axis(qkv, axis=1, begin=0, end=d_model)
                k = sym.slice_axis(qkv, axis=1, begin=d_model,
                                   end=d_model + d_kv)
                v = sym.slice_axis(qkv, axis=1, begin=d_model + d_kv,
                                   end=d_model + 2 * d_kv)
            else:
                q = sym.FullyConnected(flat, name=f"{p}_q",
                                       num_hidden=d_model)
                k = sym.FullyConnected(flat, name=f"{p}_k",
                                       num_hidden=d_kv)
                v = sym.FullyConnected(flat, name=f"{p}_v",
                                       num_hidden=d_kv)

            if attn_layout == "bshd":
                # sequence-major: (B, S, H, Dh) straight from the
                # projection reshape, no transpose in or out
                def heads(x, n):
                    return sym.Reshape(x, shape=(-1, seq_len, n,
                                                 head_dim))
            else:
                def heads(x, n):
                    x = sym.Reshape(x, shape=(-1, seq_len, n,
                                              head_dim))
                    return sym.SwapAxis(x, dim1=1, dim2=2)   # (B, n, S, Dh)

            qh, kh = heads(q, num_heads), heads(k, kv_heads)
            if pos_embed == "rope":
                qh = sym.RoPE(qh, layout=attn_layout)
                kh = sym.RoPE(kh, layout=attn_layout)
            attn = sym.FlashAttention(qh, kh, heads(v, kv_heads),
                                      name=f"{p}_attn", causal=causal,
                                      layout=attn_layout, impl=attn_impl,
                                      sp_impl=attn_sp_impl,
                                      window=attn_window)
            if attn_layout == "bshd":
                merged = sym.Reshape(attn, shape=(-1, d_model))
            else:
                merged = sym.Reshape(sym.SwapAxis(attn, dim1=1, dim2=2),
                                     shape=(-1, d_model))
            proj = sym.FullyConnected(merged, name=f"{p}_proj",
                                      num_hidden=d_model)
            if dropout > 0:
                proj = sym.Dropout(proj, p=dropout)
            h = h + sym.Reshape(proj, shape=(-1, seq_len, d_model))

            # -- MLP block (pre-LN) -------------------------------------
            ln2 = norm_layer(h, f"{p}_ln2")
            flat2 = sym.Reshape(ln2, shape=(-1, d_model))
            up = sym.FullyConnected(flat2, name=f"{p}_ff_up",
                                     num_hidden=d_ff)
            if mlp == "swiglu":
                gate = sym.FullyConnected(flat2, name=f"{p}_ff_gate",
                                          num_hidden=d_ff)
                act = sym.silu(gate) * up       # f32 silu, like gelu
            else:
                act = sym.gelu(up)
            down = sym.FullyConnected(act, name=f"{p}_ff_down",
                                      num_hidden=d_model)
            if dropout > 0:
                down = sym.Dropout(down, p=dropout)
            h = h + sym.Reshape(down, shape=(-1, seq_len, d_model))

    final = norm_layer(h, f"{name}_ln_f")
    final_flat = sym.Reshape(final, shape=(-1, d_model))
    if tie_embeddings:
        # same named variable as the Embedding: the executor binds one
        # array and sums both ops' gradient contributions
        tok_w = sym.Variable(f"{name}_tok_embed_weight")
        logits = sym.FullyConnected(final_flat, weight=tok_w,
                                    name=f"{name}_head",
                                    num_hidden=vocab_size, no_bias=True)
    else:
        logits = sym.FullyConnected(final_flat, name=f"{name}_head",
                                    num_hidden=vocab_size)
    label = sym.Variable("softmax_label")        # (batch, seq_len)
    label_flat = sym.Reshape(label, shape=(-1,))
    if loss == "ce":
        out = sym.SoftmaxCELoss(logits, label_flat, name="softmax")
    else:
        out = sym.SoftmaxOutput(logits, label_flat, name="softmax")
    # decode-time config NOT derivable from weight shapes (generate.py
    # detects kv_heads/rope/swiglu/tied from the checkpoint, but head
    # count and the trained sliding window are invisible there) —
    # persist it in the symbol so the two-artifact checkpoint carries it
    out._set_attr(__gpt_num_heads__=num_heads, __gpt_attn_window__=attn_window)
    return out
