"""Incremental (KV-cache) decoding for the GPT model family.

Training runs the full-sequence graph (models/transformer.py); serving
wants O(1) work per generated token.  This module rebuilds the decoder
as a single-token step over cached keys/values and runs the WHOLE
generation loop as one ``lax.scan`` inside one jit — prompt prefill and
sampling included — so a generate call is one XLA program dispatch with
the cache resident in HBM (the TPU-idiomatic shape for autoregressive
serving; contrast the reference's per-step executor calls in
example/rnn char-rnn style inference).

Operates directly on a trained parameter dict (``Module.get_params()``
/ ``FeedForward`` checkpoints / ``ShardedTrainer.get_params()``), with
a parity test against the training graph in
``tests/test_generate.py``.
"""

from __future__ import annotations

import warnings

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["gpt_generate", "gpt_decode_config", "normalize_gpt_params",
           "detect_gpt_variant", "reconcile_decode_config"]

_decoder_cache = {}


def _ln(x, gamma, beta, eps=1e-5):
    xf = x.astype(jnp.float32)
    if beta is None:          # rmsnorm checkpoint: no shift, no centering
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        return (xf * jax.lax.rsqrt(ms + eps)
                * gamma.astype(jnp.float32)).astype(x.dtype)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    return (y * gamma.astype(jnp.float32)
            + beta.astype(jnp.float32)).astype(x.dtype)


def _fc(x, w, b):
    return x @ w.T.astype(x.dtype) + b.astype(x.dtype)


def _gelu(x):
    xf = x.astype(jnp.float32)
    return (0.5 * xf * (1.0 + jax.lax.erf(xf / np.sqrt(2.0)))).astype(x.dtype)


def gpt_decode_config(symbol):
    """Decode-time config a :func:`mxnet_tpu.models.gpt` symbol carries
    that is NOT recoverable from weight shapes: ``num_heads`` and the
    trained sliding-window radius (``attn_window``).  Works on a freshly
    built symbol or one round-tripped through the two-artifact
    checkpoint (``model.load_checkpoint``), since node attrs serialize.
    Returns ``{"num_heads": int, "window": int}``; raises if the symbol
    carries no gpt config attrs (predates them, or not a gpt symbol)."""
    heads = symbol.attr("__gpt_num_heads__")
    if heads is None:
        raise ValueError(
            "symbol carries no __gpt_num_heads__ attr — not built by "
            "models.gpt(), or saved before decode-config persistence; "
            "pass num_heads/window to gpt_generate explicitly")
    return {"num_heads": int(heads),
            "window": int(symbol.attr("__gpt_attn_window__") or 0)}


def reconcile_decode_config(symbol, num_heads, window):
    """Merge explicit ``num_heads``/``window`` overrides with the
    symbol's persisted decode config (:func:`gpt_decode_config`),
    raising on contradiction — the reshapes would succeed either way
    and silently decode garbage.  Shared by :func:`gpt_generate` and
    ``serve.Engine`` so the two decoders cannot drift.  Returns the
    resolved ``(num_heads, window)``."""
    cfg = gpt_decode_config(symbol)
    if num_heads is None:
        num_heads = cfg["num_heads"]
    elif int(num_heads) != cfg["num_heads"]:
        raise ValueError(
            f"num_heads={num_heads} contradicts the symbol's "
            f"num_heads={cfg['num_heads']} — the reshapes would "
            "succeed and decode garbage")
    if window is None:
        window = cfg["window"]
    elif int(window) != cfg["window"]:
        raise ValueError(
            f"window={window} contradicts the symbol's trained "
            f"attn_window={cfg['window']} — decoding with a "
            "different window silently changes the model")
    return num_heads, window


def normalize_gpt_params(params, name="gpt"):
    """Canonicalize a gpt() checkpoint for decoding: dequantize
    weight-only-int8 entries (``*_wscale``) and split ``fused_qkv``
    projections back into the per-tensor ``*_{q,k,v}_*`` layout every
    decoder (generate.py's scan loop, serve.Engine's paged steps)
    addresses.  Returns the input dict unchanged when neither applies.
    """
    try:
        tok_w = params[f"{name}_tok_embed_weight"]
    except KeyError:
        raise ValueError(
            f"params has no '{name}_tok_embed_weight' — wrong name "
            "prefix or not a gpt() parameter dict") from None
    d_model = tok_w.shape[1]
    if any(k.endswith("_wscale") for k in params):
        # quantized checkpoint (contrib/quantization.py): dequantize the
        # int8 weights once at load — decode then runs the normal path
        # (weight-only int8 semantics)
        params = dict(params)
        for k in [k for k in params if k.endswith("_wscale")]:
            stem = k[: -len("_wscale")]
            wq = np.asarray(params[stem + "_weight"], np.float32)
            scale = np.asarray(params.pop(k), np.float32)
            params[stem + "_weight"] = wq * scale[:, None]
    if f"{name}_l0_qkv_weight" in params:
        # fused_qkv=True checkpoint layout: split each projection back
        # into the q/k/v entries the decoder addresses.  GQA fused
        # checkpoints emit (d_model + 2*d_kv) rows, so split at the
        # boundaries rather than in thirds.
        params = dict(params)
        rows = np.asarray(params[f"{name}_l0_qkv_weight"]).shape[0]
        d_kv_f = (rows - d_model) // 2
        i = 0
        while f"{name}_l{i}_qkv_weight" in params:
            for kind in ("weight", "bias"):
                whole = np.asarray(params.pop(f"{name}_l{i}_qkv_{kind}"))
                parts = np.split(whole, [d_model, d_model + d_kv_f],
                                 axis=0)
                for x, part in zip(("q", "k", "v"), parts):
                    params[f"{name}_l{i}_{x}_{kind}"] = part
            i += 1
    return params


def detect_gpt_variant(params, num_heads, name="gpt"):
    """Model-variant flags recoverable from a NORMALIZED checkpoint
    (see :func:`normalize_gpt_params`): layer count, head-dim split,
    grouped-query kv_heads, rope-vs-learned positions (``pos_table`` is
    the table length, None for rope), SwiGLU MLP, tied LM head, and
    rmsnorm.  ``num_heads`` itself is NOT recoverable from shapes —
    callers read it from the symbol (gpt_decode_config) or take it
    explicitly."""
    tok_w = params[f"{name}_tok_embed_weight"]
    d_model = tok_w.shape[1]
    pos_w = params.get(f"{name}_pos_embed_weight")
    n_layers = 0
    while f"{name}_l{n_layers}_q_weight" in params:
        n_layers += 1
    if n_layers == 0:
        raise ValueError(f"no '{name}_l0_q_weight' (or '_l0_qkv_weight') "
                         f"in params — wrong name prefix or not a gpt() "
                         "parameter dict")
    if d_model % num_heads:
        raise ValueError("num_heads must divide d_model")
    head_dim = d_model // num_heads
    return {
        "n_layers": n_layers,
        "d_model": d_model,
        "head_dim": head_dim,
        "kv_heads": (np.asarray(params[f"{name}_l0_k_weight"]).shape[0]
                     // head_dim),
        "vocab": tok_w.shape[0],
        "pos_table": None if pos_w is None else pos_w.shape[1],
        "swiglu": f"{name}_l0_ff_gate_weight" in params,
        "tied": f"{name}_head_weight" not in params,
        "rmsnorm": f"{name}_l0_ln1_beta" not in params,
    }


def gpt_generate(params, prompt, max_new_tokens, num_heads=None,
                 temperature=0.0, top_k=None, key=None, window=None,
                 name="gpt", symbol=None):
    """Generate continuations for ``prompt`` with a KV cache.

    Args:
      params: dict name->array of trained GPT weights (numpy or jax),
        with the naming of :func:`mxnet_tpu.models.gpt`.
      prompt: int array (batch, prompt_len) of token ids.
      max_new_tokens: tokens to append after the prompt.
      num_heads: attention head count the model was built with (not
        recoverable from weight shapes).
      temperature: 0.0 -> greedy argmax; otherwise sample from
        softmax(logits / temperature).
      top_k: optionally restrict sampling to the k most likely tokens.
      key: jax PRNG key for sampling (defaults to PRNGKey(0)).
      window: sliding-window radius the model was TRAINED with
        (models.gpt attn_window); 0 = full attention.
      name: the symbol-name prefix used when building the model.

    Model variants are detected from the checkpoint itself: the K
    projection's row count gives kv_heads (grouped-query attention), a
    missing position table means rope, an ``*_ff_gate_weight`` means a
    SwiGLU MLP, and a missing ``*_head_weight`` means the LM head is
    the tied token-embedding matrix.

    Returns ``(batch, prompt_len + max_new_tokens)`` numpy int32 ids
    (prompt included).  The compiled decode loop is cached per
    (config, shapes) so repeated calls don't re-trace.
    """
    prompt = np.asarray(prompt)
    if prompt.ndim != 2:
        raise ValueError("prompt must be (batch, prompt_len)")
    if symbol is not None:
        num_heads, window = reconcile_decode_config(symbol, num_heads,
                                                    window)
    if num_heads is None:
        raise ValueError("num_heads is required (pass it, or pass "
                         "symbol= to read it from the trained graph)")
    if window is None:
        # not auto-detectable from weights alone: a window-trained
        # checkpoint decoded without window= would silently run full
        # attention.  Explicit window=0 (or symbol=) silences this.
        warnings.warn(
            "gpt_generate: window not given and no symbol= to detect it "
            "from; assuming full attention (window=0). If the model was "
            "trained with attn_window>0 this is a silent mismatch — "
            "pass window= or symbol=.", stacklevel=2)
        window = 0
    if window < 0:
        raise ValueError(f"window must be >= 0 (got {window})")
    B, P = prompt.shape
    if P < 1:
        raise ValueError("prompt must hold at least one token")

    params = normalize_gpt_params(params, name)
    spec = detect_gpt_variant(params, num_heads, name)
    tok_w = params[f"{name}_tok_embed_weight"]
    n_layers, head_dim = spec["n_layers"], spec["head_dim"]
    kv_heads = spec["kv_heads"]
    swiglu, tied, rmsnorm = spec["swiglu"], spec["tied"], spec["rmsnorm"]
    # pos_embed="rope" checkpoints carry no position table; positions
    # then have no trained length limit, so the cache sizes to the
    # request instead of the table
    S = spec["pos_table"]
    T = P + max_new_tokens
    if S is not None and T > S:
        raise ValueError(
            f"prompt_len + max_new_tokens = {T} exceeds the model's "
            f"positional table ({S})")
    S_cache = T if S is None else S

    if max_new_tokens < 1:
        return np.asarray(prompt, np.int32)

    cfg = (name, n_layers, num_heads, head_dim, B, P, max_new_tokens,
           S_cache, float(temperature), top_k, kv_heads, S is None,
           int(window), swiglu, tied, rmsnorm,
           str(jnp.asarray(tok_w).dtype))
    run = _decoder_cache.get(cfg)
    if run is None:
        run = _build_decoder(name, n_layers, num_heads, head_dim, B, P,
                             max_new_tokens, S_cache, float(temperature),
                             top_k, kv_heads=kv_heads, rope=S is None,
                             window=int(window), swiglu=swiglu, tied=tied,
                             rmsnorm=rmsnorm)
        _decoder_cache[cfg] = run

    if key is None:
        key = jax.random.PRNGKey(0)
    jparams = {k: jnp.asarray(v) for k, v in params.items()}
    ids = run(jparams, jnp.asarray(prompt, jnp.int32), key)
    return np.asarray(jax.device_get(ids), np.int32)


def _build_decoder(name, n_layers, num_heads, head_dim, B, P,
                   max_new_tokens, S, temperature, top_k, kv_heads=None,
                   rope=False, window=0, swiglu=False, tied=False,
                   rmsnorm=False):
    d_model = num_heads * head_dim
    T = P + max_new_tokens
    kv_heads = kv_heads or num_heads
    group = num_heads // kv_heads
    half = head_dim // 2

    def _rot(u, t):
        """RoPE rotation of (B, H, Dh) at scalar position t (matches
        ops/attention.py RoPEOp with offset folded into t)."""
        inv = 10000.0 ** (-jnp.arange(half, dtype=jnp.float32) / half)
        ang = t.astype(jnp.float32) * inv                     # (half,)
        cos, sin = jnp.cos(ang), jnp.sin(ang)
        uf = u.astype(jnp.float32)
        u1, u2 = uf[..., :half], uf[..., half:]
        return jnp.concatenate([u1 * cos - u2 * sin,
                                u1 * sin + u2 * cos],
                               axis=-1).astype(u.dtype)

    def step_token(params, tok, t, cache_k, cache_v):
        """One decode position: tok (B,) int32 at position t; caches
        (L, B, Hkv, S, Dh).  Returns logits (B, V) + updated caches."""
        x = params[f"{name}_tok_embed_weight"][tok]            # (B, D)
        if not rope:
            x = x + params[f"{name}_pos_embed_weight"][0, t]
        pos_mask = (jnp.arange(S) <= t)                        # (S,)
        if window:
            pos_mask = jnp.logical_and(pos_mask,
                                       jnp.arange(S) > t - window)
        for i in range(n_layers):
            p = f"{name}_l{i}"
            h = _ln(x, params[f"{p}_ln1_gamma"],
                    None if rmsnorm else params[f"{p}_ln1_beta"])
            q = _fc(h, params[f"{p}_q_weight"], params[f"{p}_q_bias"])
            k = _fc(h, params[f"{p}_k_weight"], params[f"{p}_k_bias"])
            v = _fc(h, params[f"{p}_v_weight"], params[f"{p}_v_bias"])
            qh = q.reshape(B, num_heads, head_dim)
            kh = k.reshape(B, kv_heads, head_dim)
            vh = v.reshape(B, kv_heads, head_dim)
            if rope:
                qh, kh = _rot(qh, t), _rot(kh, t)
            # write this token's k/v at position t, then attend over <=t
            cache_k = cache_k.at[i, :, :, t, :].set(kh)
            cache_v = cache_v.at[i, :, :, t, :].set(vh)
            # grouped-query: kv head g serves q heads [g*group, ...)
            qg = qh.reshape(B, kv_heads, group, head_dim)
            scores = jnp.einsum("bkgd,bksd->bkgs", qg, cache_k[i])
            scores = scores / np.sqrt(head_dim)
            scores = jnp.where(pos_mask[None, None, None, :], scores,
                               -jnp.inf)
            probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
            attn = jnp.einsum("bkgs,bksd->bkgd", probs.astype(x.dtype),
                              cache_v[i])
            x = x + _fc(attn.reshape(B, d_model),
                        params[f"{p}_proj_weight"], params[f"{p}_proj_bias"])
            h2 = _ln(x, params[f"{p}_ln2_gamma"],
                     None if rmsnorm else params[f"{p}_ln2_beta"])
            if swiglu:
                g = _fc(h2, params[f"{p}_ff_gate_weight"],
                        params[f"{p}_ff_gate_bias"])
                gf = g.astype(jnp.float32)       # f32 silu == sym.silu
                up = ((gf * jax.nn.sigmoid(gf)).astype(g.dtype)
                      * _fc(h2, params[f"{p}_ff_up_weight"],
                            params[f"{p}_ff_up_bias"]))
            else:
                up = _gelu(_fc(h2, params[f"{p}_ff_up_weight"],
                               params[f"{p}_ff_up_bias"]))
            x = x + _fc(up, params[f"{p}_ff_down_weight"],
                        params[f"{p}_ff_down_bias"])
        final = _ln(x, params[f"{name}_ln_f_gamma"],
                    None if rmsnorm else params[f"{name}_ln_f_beta"])
        if tied:
            # tied checkpoint: the LM head is the embedding matrix
            logits = final @ params[f"{name}_tok_embed_weight"].T.astype(
                final.dtype)
        else:
            logits = _fc(final, params[f"{name}_head_weight"],
                         params[f"{name}_head_bias"])
        return logits, cache_k, cache_v

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = logits.astype(jnp.float32) / temperature
        if top_k is not None:
            kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
            logits = jnp.where(logits >= kth, logits, -jnp.inf)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    def run(params, prompt, key):
        cache_k = jnp.zeros((n_layers, B, kv_heads, S, head_dim),
                            params[f"{name}_tok_embed_weight"].dtype)
        cache_v = jnp.zeros_like(cache_k)
        # tokens fed at each step: prompt for t < P, then sampled
        prompt_t = jnp.transpose(prompt)                      # (P, B)

        def body(carry, t):
            cache_k, cache_v, next_tok, key = carry
            tok = jnp.where(t < P,
                            prompt_t[jnp.minimum(t, P - 1)], next_tok)
            logits, cache_k, cache_v = step_token(params, tok, t,
                                                  cache_k, cache_v)
            key, sub = jax.random.split(key)
            sampled = sample(logits, sub)
            return (cache_k, cache_v, sampled, key), (tok, sampled)

        init = (cache_k, cache_v, jnp.zeros((B,), jnp.int32), key)
        _, (fed, sampled) = jax.lax.scan(body, init, jnp.arange(T - 1))
        # position t's sample is the token for position t+1; the ids
        # actually consumed are fed[0:T-1] plus the final sample
        ids = jnp.concatenate([fed, sampled[-1:]], axis=0)    # (T, B)
        return jnp.transpose(ids)

    return jax.jit(run)
