"""SSD object detector (reference example/ssd/symbol/symbol_vgg16_reduced.py
+ example/ssd/symbol/common.py multibox head, using the MultiBox ops).

``get_symbol(..., mode="train")`` emits the training graph (multibox
target matching + softmax cls loss + smooth-L1 loc loss); ``mode="det"``
emits the detection graph (decode + NMS).
"""

from __future__ import annotations

import numpy as np

from .. import symbol as mx_sym


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1), stride=(1, 1)):
    c = mx_sym.Convolution(data, kernel=kernel, pad=pad, stride=stride,
                           num_filter=num_filter, name=f"conv{name}")
    return mx_sym.Activation(c, act_type="relu", name=f"relu{name}")


def vgg16_reduced(data, fs=1):
    """VGG16 with reduced fc6/fc7 as dilated convs (symbol_vgg16_reduced.py).

    ``fs`` divides all channel widths (testing knob; 1 = reference arch).
    Returns (relu4_3, relu7) feature maps."""
    x = data
    for i, (n_convs, nf) in enumerate([(2, 64), (2, 128), (3, 256)], 1):
        for j in range(n_convs):
            x = _conv_act(x, f"{i}_{j + 1}", nf // fs)
        x = mx_sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2),
                           pooling_convention="full", name=f"pool{i}")
    for j in range(3):
        x = _conv_act(x, f"4_{j + 1}", 512 // fs)
    relu4_3 = x
    x = mx_sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2),
                       pooling_convention="full", name="pool4")
    for j in range(3):
        x = _conv_act(x, f"5_{j + 1}", 512 // fs)
    x = mx_sym.Pooling(x, pool_type="max", kernel=(3, 3), stride=(1, 1),
                       pad=(1, 1), name="pool5")
    # fc6 as dilated conv, fc7 as 1x1 (the "reduced" trick)
    fc6 = mx_sym.Convolution(x, kernel=(3, 3), pad=(6, 6), dilate=(6, 6),
                             num_filter=1024 // fs, name="fc6")
    relu6 = mx_sym.Activation(fc6, act_type="relu", name="relu6")
    fc7 = mx_sym.Convolution(relu6, kernel=(1, 1), num_filter=1024 // fs, name="fc7")
    relu7 = mx_sym.Activation(fc7, act_type="relu", name="relu7")
    return relu4_3, relu7


def _extra_layers(relu7, fs=1):
    """Conv8-conv11 pyramid (example/ssd/symbol/common.py multi_layer_feature)."""
    layers = [relu7]
    x = relu7
    specs = [(256, 512, 2), (128, 256, 2), (128, 256, 1), (128, 256, 1)]
    for i, (nf1, nf2, stride) in enumerate(specs, 8):
        x = _conv_act(x, f"{i}_1", nf1 // fs, kernel=(1, 1), pad=(0, 0))
        pad = (1, 1) if stride == 2 else (0, 0)
        x = _conv_act(x, f"{i}_2", nf2 // fs, kernel=(3, 3), pad=pad,
                      stride=(stride, stride))
        layers.append(x)
    return layers


_SIZES = [(0.1, 0.141), (0.2, 0.272), (0.37, 0.447), (0.54, 0.619),
          (0.71, 0.79), (0.88, 0.961)]
_RATIOS = [(1.0, 2.0, 0.5)] * 2 + [(1.0, 2.0, 0.5, 3.0, 1.0 / 3)] * 3 + \
    [(1.0, 2.0, 0.5)]


def multibox_layer(from_layers, num_classes, sizes=None, ratios=None,
                   clip=True):
    """Per-scale loc/cls heads + anchors (common.py multibox_layer)."""
    sizes = sizes or _SIZES
    ratios = ratios or _RATIOS
    loc_layers, cls_layers, anchor_layers = [], [], []
    num_classes_b = num_classes + 1  # + background
    for i, layer in enumerate(from_layers):
        n_anchor = len(sizes[i]) + len(ratios[i]) - 1
        loc = mx_sym.Convolution(layer, kernel=(3, 3), pad=(1, 1),
                                 num_filter=n_anchor * 4,
                                 name=f"loc_pred_conv{i}")
        # (N, A*4, H, W) -> (N, H, W, A*4) -> flat
        loc = mx_sym.transpose(loc, axes=(0, 2, 3, 1))
        loc_layers.append(mx_sym.Flatten(loc))
        cls = mx_sym.Convolution(layer, kernel=(3, 3), pad=(1, 1),
                                 num_filter=n_anchor * num_classes_b,
                                 name=f"cls_pred_conv{i}")
        cls = mx_sym.transpose(cls, axes=(0, 2, 3, 1))
        cls_layers.append(mx_sym.Flatten(cls))
        anchors = mx_sym.MultiBoxPrior(layer, sizes=sizes[i], ratios=ratios[i],
                                       clip=clip, name=f"anchors{i}")
        anchor_layers.append(mx_sym.Reshape(anchors, shape=(-1, 4)))
    loc_preds = mx_sym.Concat(*loc_layers, num_args=len(loc_layers), dim=1,
                              name="multibox_loc_pred")
    cls_concat = mx_sym.Concat(*cls_layers, num_args=len(cls_layers), dim=1)
    cls_preds = mx_sym.Reshape(cls_concat, shape=(0, -1, num_classes_b))
    cls_preds = mx_sym.transpose(cls_preds, axes=(0, 2, 1),
                                 name="multibox_cls_pred")
    anchors_c = mx_sym.Concat(*anchor_layers, num_args=len(anchor_layers),
                              dim=0)
    anchor_boxes = mx_sym.Reshape(anchors_c, shape=(1, -1, 4),
                                  name="multibox_anchors")
    return loc_preds, cls_preds, anchor_boxes


def get_symbol(num_classes=20, mode="train", nms_thresh=0.5, nms_topk=400,
               filter_scale=1, **kwargs):
    fs = filter_scale
    data = mx_sym.Variable("data")
    relu4_3, relu7 = vgg16_reduced(data, fs)
    # L2-normalize conv4_3 feature like the reference, with learned scale
    norm4_3 = mx_sym.L2Normalization(relu4_3, mode="channel",
                                     name="relu4_3_norm")
    scale_var = mx_sym.Variable("relu4_3_scale", shape=(1, 512 // fs, 1, 1))
    norm4_3 = mx_sym.broadcast_mul(norm4_3, scale_var)
    layers = [norm4_3] + _extra_layers(relu7, fs)
    loc_preds, cls_preds, anchors = multibox_layer(layers, num_classes)

    if mode == "det":
        cls_prob = mx_sym.SoftmaxActivation(cls_preds, mode="channel",
                                            name="cls_prob")
        return mx_sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                        nms_threshold=nms_thresh, clip=True,
                                        nms_topk=nms_topk, name="detection")

    label = mx_sym.Variable("label")
    tgt = mx_sym.MultiBoxTarget(anchors, label, cls_preds,
                                overlap_threshold=0.5,
                                ignore_label=-1, negative_mining_ratio=3.0,
                                minimum_negative_samples=0,
                                negative_mining_thresh=0.5,
                                name="multibox_target")
    loc_target, loc_target_mask, cls_target = tgt[0], tgt[1], tgt[2]
    cls_prob = mx_sym.SoftmaxOutput(cls_preds, cls_target,
                                    ignore_label=-1, use_ignore=True,
                                    multi_output=True,
                                    normalization="valid", name="cls_prob")
    loc_diff = loc_preds - loc_target
    masked_loc = loc_target_mask * loc_diff
    loc_loss_ = mx_sym.smooth_l1(masked_loc, sigma=1.0, name="loc_loss_")
    loc_loss = mx_sym.MakeLoss(loc_loss_, grad_scale=1.0,
                               normalization="valid", name="loc_loss")
    # monitoring outputs (blocked grads), same as reference train symbol
    cls_label = mx_sym.MakeLoss(cls_target, grad_scale=0, name="cls_label")
    return mx_sym.Group([cls_prob, loc_loss, cls_label])
