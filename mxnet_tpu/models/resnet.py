"""ResNet (reference example/image-classification/symbol_resnet.py,
generalized to the standard depth configs used by train_imagenet.py).

The BASELINE north-star model: ResNet-50 on ImageNet, data-parallel over
the chip mesh.  BatchNorm uses fix_gamma=False like the reference resnet
symbol; blocks are the bottleneck variant for depth >= 50.
"""

from .. import symbol as mx_sym


def residual_unit(data, num_filter, stride, dim_match, name, bottle_neck=True,
                  bn_mom=0.9, workspace=512, layout="NCHW"):
    bn_axis = -1 if layout == "NHWC" else 1
    if bottle_neck:
        bn1 = mx_sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                               axis=bn_axis, name=name + "_bn1")
        act1 = mx_sym.Activation(bn1, act_type="relu", name=name + "_relu1")
        conv1 = mx_sym.Convolution(act1, layout=layout, num_filter=num_filter // 4,
                                   kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                   no_bias=True, workspace=workspace,
                                   name=name + "_conv1")
        bn2 = mx_sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5,
                               momentum=bn_mom, axis=bn_axis, name=name + "_bn2")
        act2 = mx_sym.Activation(bn2, act_type="relu", name=name + "_relu2")
        conv2 = mx_sym.Convolution(act2, layout=layout, num_filter=num_filter // 4,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, workspace=workspace,
                                   name=name + "_conv2")
        bn3 = mx_sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5,
                               momentum=bn_mom, axis=bn_axis, name=name + "_bn3")
        act3 = mx_sym.Activation(bn3, act_type="relu", name=name + "_relu3")
        conv3 = mx_sym.Convolution(act3, layout=layout, num_filter=num_filter, kernel=(1, 1),
                                   stride=(1, 1), pad=(0, 0), no_bias=True,
                                   workspace=workspace, name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = mx_sym.Convolution(act1, layout=layout, num_filter=num_filter,
                                          kernel=(1, 1), stride=stride,
                                          no_bias=True, workspace=workspace,
                                          name=name + "_sc")
        return conv3 + shortcut
    bn1 = mx_sym.BatchNorm(data, fix_gamma=False, momentum=bn_mom, eps=2e-5,
                           axis=bn_axis, name=name + "_bn1")
    act1 = mx_sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    conv1 = mx_sym.Convolution(act1, layout=layout, num_filter=num_filter, kernel=(3, 3),
                               stride=stride, pad=(1, 1), no_bias=True,
                               workspace=workspace, name=name + "_conv1")
    bn2 = mx_sym.BatchNorm(conv1, fix_gamma=False, momentum=bn_mom, eps=2e-5,
                           axis=bn_axis, name=name + "_bn2")
    act2 = mx_sym.Activation(bn2, act_type="relu", name=name + "_relu2")
    conv2 = mx_sym.Convolution(act2, layout=layout, num_filter=num_filter, kernel=(3, 3),
                               stride=(1, 1), pad=(1, 1), no_bias=True,
                               workspace=workspace, name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = mx_sym.Convolution(act1, layout=layout, num_filter=num_filter, kernel=(1, 1),
                                      stride=stride, no_bias=True,
                                      workspace=workspace, name=name + "_sc")
    return conv2 + shortcut


def resnet(units, num_stage, filter_list, num_class, bottle_neck=True,
           bn_mom=0.9, workspace=512, small_input=False, layout="NCHW",
           stem="conv7"):
    bn_axis = -1 if layout == "NHWC" else 1
    data = mx_sym.Variable("data")
    data = mx_sym.BatchNorm(data, fix_gamma=True, eps=2e-5, momentum=bn_mom,
                            axis=bn_axis, name="bn_data")
    if small_input and stem != "conv7":
        raise ValueError(
            f"stem={stem!r} conflicts with small_input: the cifar-style "
            "3x3 stem takes raw HxWxC images, not s2d-transformed input")
    if small_input:  # cifar-style stem
        body = mx_sym.Convolution(data, layout=layout, num_filter=filter_list[0],
                                  kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                                  no_bias=True, name="conv0",
                                  workspace=workspace)
    elif stem == "s2d":
        # Space-to-depth stem (the standard TPU trick): the caller feeds
        # data already transformed to (N, H/2, W/2, 4C) NHWC, and the
        # 7x7/s2 conv becomes a dense 4x4/s1 conv — C=3 wastes all but 3
        # of the MXU's 128 input lanes; C=12 with stride 1 is 4x denser
        # and removes the strided backward pass.  Receptive field
        # matches the 7x7 (8x8 zero-padded) conv; train-from-scratch
        # equivalent, not checkpoint-compatible with stem="conv7".
        if layout != "NHWC":
            raise ValueError("s2d stem requires NHWC layout")
        body = mx_sym.Pad(data, mode="constant",
                          pad_width=(0, 0, 2, 1, 2, 1, 0, 0))
        body = mx_sym.Convolution(body, layout=layout,
                                  num_filter=filter_list[0],
                                  kernel=(4, 4), stride=(1, 1), pad=(0, 0),
                                  no_bias=True, name="conv0",
                                  workspace=workspace)
        body = mx_sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                                momentum=bn_mom, axis=bn_axis, name="bn0")
        body = mx_sym.Activation(body, act_type="relu", name="relu0")
        body = mx_sym.Pooling(body, layout=layout, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                              pool_type="max")
    else:  # imagenet stem
        body = mx_sym.Convolution(data, layout=layout, num_filter=filter_list[0],
                                  kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                                  no_bias=True, name="conv0",
                                  workspace=workspace)
        body = mx_sym.BatchNorm(body, fix_gamma=False, eps=2e-5,
                                momentum=bn_mom, axis=bn_axis, name="bn0")
        body = mx_sym.Activation(body, act_type="relu", name="relu0")
        body = mx_sym.Pooling(body, layout=layout, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                              pool_type="max")
    for i in range(num_stage):
        body = residual_unit(body, filter_list[i + 1],
                             (1 if i == 0 else 2, 1 if i == 0 else 2), False,
                             name=f"stage{i + 1}_unit1",
                             bottle_neck=bottle_neck, bn_mom=bn_mom,
                             workspace=workspace, layout=layout)
        for j in range(units[i] - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 name=f"stage{i + 1}_unit{j + 2}",
                                 bottle_neck=bottle_neck, bn_mom=bn_mom,
                                 workspace=workspace, layout=layout)
    bn1 = mx_sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                           axis=bn_axis, name="bn1")
    relu1 = mx_sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = mx_sym.Pooling(relu1, layout=layout, global_pool=True, kernel=(7, 7),
                           pool_type="avg", name="pool1")
    flat = mx_sym.Flatten(pool1)
    fc1 = mx_sym.FullyConnected(flat, num_hidden=num_class, name="fc1")
    return mx_sym.SoftmaxOutput(fc1, name="softmax")


_DEPTH_CONFIGS = {
    18: ([2, 2, 2, 2], False),
    34: ([3, 4, 6, 3], False),
    50: ([3, 4, 6, 3], True),
    101: ([3, 4, 23, 3], True),
    152: ([3, 8, 36, 3], True),
}


def get_symbol(num_classes=1000, num_layers=50, image_shape=(3, 224, 224),
               workspace=512, layout="NCHW", stem="conv7"):
    if num_layers not in _DEPTH_CONFIGS:
        raise ValueError(f"unsupported depth {num_layers}")
    units, bottle_neck = _DEPTH_CONFIGS[num_layers]
    if bottle_neck:
        filter_list = [64, 256, 512, 1024, 2048]
    else:
        filter_list = [64, 64, 128, 256, 512]
    small = image_shape[-1] < 64
    return resnet(units=units, num_stage=4, filter_list=filter_list,
                  num_class=num_classes, bottle_neck=bottle_neck,
                  workspace=workspace, small_input=small, layout=layout,
                  stem=stem)
