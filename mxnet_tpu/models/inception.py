"""Inception-BN model family (reference example/image-classification/
symbol_inception-bn.py and symbol_inception-bn-28-small.py).

These are the reference's published-baseline workloads: CIFAR-10
"inception-bn-28-small" is the 1/2/4-GPU img/sec table and ImageNet
Inception-BN the epoch-time table (SURVEY.md §6).  Table-driven rebuild:
one mixed-block builder consumes per-stage branch configs instead of
per-block factory calls; supports NHWC layout for TPU.
"""

from .. import symbol as mx_sym

_EPS = 1e-10 + 1e-5
_BN_MOM = 0.9


def _conv_bn(data, num_filter, kernel, name, stride=(1, 1), pad=(0, 0),
             layout="NCHW"):
    bn_axis = -1 if layout == "NHWC" else 1
    x = mx_sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, layout=layout,
                           name=f"conv_{name}")
    x = mx_sym.BatchNorm(x, fix_gamma=False, eps=_EPS, momentum=_BN_MOM,
                         axis=bn_axis, name=f"bn_{name}")
    return mx_sym.Activation(x, act_type="relu", name=f"relu_{name}")


def _mixed(data, name, branches, layout="NCHW"):
    """One inception block.  ``branches`` is a list of branch specs:
    - ("conv", [(filters, kernel, stride, pad), ...])   chain of conv-bn
    - ("pool", pool_type, stride, proj_filters_or_None) pool (+ 1x1 proj)
    Branch outputs concat on the channel axis."""
    concat_axis = -1 if layout == "NHWC" else 1
    outs = []
    for bi, spec in enumerate(branches):
        if spec[0] == "conv":
            x = data
            for ci, (nf, k, s, p) in enumerate(spec[1]):
                x = _conv_bn(x, nf, k, f"{name}_b{bi}_{ci}", stride=s, pad=p,
                             layout=layout)
            outs.append(x)
        else:
            _, pool_type, stride, proj = spec
            x = mx_sym.Pooling(data, kernel=(3, 3), stride=stride, pad=(1, 1),
                               pool_type=pool_type, layout=layout,
                               name=f"pool_{name}_b{bi}")
            if proj is not None:
                x = _conv_bn(x, proj, (1, 1), f"{name}_b{bi}_proj",
                             layout=layout)
            outs.append(x)
    return mx_sym.Concat(*outs, num_args=len(outs), dim=concat_axis,
                         name=f"concat_{name}")


def _stage_a(n1, nr3, n3, nrd3, nd3, pool, proj):
    """Reference InceptionFactoryA branch table."""
    return [
        ("conv", [(n1, (1, 1), (1, 1), (0, 0))]),
        ("conv", [(nr3, (1, 1), (1, 1), (0, 0)),
                  (n3, (3, 3), (1, 1), (1, 1))]),
        ("conv", [(nrd3, (1, 1), (1, 1), (0, 0)),
                  (nd3, (3, 3), (1, 1), (1, 1)),
                  (nd3, (3, 3), (1, 1), (1, 1))]),
        ("pool", pool, (1, 1), proj),
    ]


def _stage_b(nr3, n3, nrd3, nd3):
    """Reference InceptionFactoryB (stride-2 grid reduction)."""
    return [
        ("conv", [(nr3, (1, 1), (1, 1), (0, 0)),
                  (n3, (3, 3), (2, 2), (1, 1))]),
        ("conv", [(nrd3, (1, 1), (1, 1), (0, 0)),
                  (nd3, (3, 3), (1, 1), (1, 1)),
                  (nd3, (3, 3), (2, 2), (1, 1))]),
        ("pool", "max", (2, 2), None),
    ]


# the reference get_symbol() block sequence, as data
_IMAGENET_BLOCKS = [
    ("3a", _stage_a(64, 64, 64, 64, 96, "avg", 32)),
    ("3b", _stage_a(64, 64, 96, 64, 96, "avg", 64)),
    ("3c", _stage_b(128, 160, 64, 96)),
    ("4a", _stage_a(224, 64, 96, 96, 128, "avg", 128)),
    ("4b", _stage_a(192, 96, 128, 96, 128, "avg", 128)),
    ("4c", _stage_a(160, 128, 160, 128, 160, "avg", 128)),
    ("4d", _stage_a(96, 128, 192, 160, 192, "avg", 128)),
    ("4e", _stage_b(128, 192, 192, 256)),
    ("5a", _stage_a(352, 192, 320, 160, 224, "avg", 128)),
    ("5b", _stage_a(352, 192, 320, 192, 224, "max", 128)),
]


def inception_bn(num_classes=1000, layout="NCHW"):
    """Inception-BN for ~224x224 inputs (symbol_inception-bn.py)."""
    data = mx_sym.Variable("data")
    x = _conv_bn(data, 64, (7, 7), "1", stride=(2, 2), pad=(3, 3),
                 layout=layout)
    x = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       layout=layout, name="pool_1")
    x = _conv_bn(x, 64, (1, 1), "2_red", layout=layout)
    x = _conv_bn(x, 192, (3, 3), "2", pad=(1, 1), layout=layout)
    x = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       layout=layout, name="pool_2")
    for name, branches in _IMAGENET_BLOCKS:
        x = _mixed(x, name, branches, layout=layout)
    x = mx_sym.Pooling(x, kernel=(7, 7), stride=(1, 1), pool_type="avg",
                       layout=layout, name="global_pool")
    x = mx_sym.Flatten(x, name="flatten")
    x = mx_sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx_sym.SoftmaxOutput(x, name="softmax")


# (1x1 filters, 3x3 filters) per simple block; None = downsample block
_SMALL_BLOCKS = [
    ("3a", (32, 32)), ("3b", (32, 48)), ("3c", (None, 80)),
    ("4a", (112, 48)), ("4b", (96, 64)), ("4c", (80, 80)),
    ("4d", (48, 96)), ("4e", (None, 96)),
    ("5a", (176, 160)), ("5b", (176, 160)),
]


def _conv_relu(data, num_filter, kernel, name, stride=(1, 1), pad=(0, 0),
               layout="NCHW"):
    """Conv + ReLU without BN (GoogLeNet v1 blocks)."""
    x = mx_sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, layout=layout,
                           name=f"conv_{name}")
    return mx_sym.Activation(x, act_type="relu", name=f"relu_{name}")


# GoogLeNet block table: (1x1, 3x3red, 3x3, 5x5red, 5x5, proj) per block,
# None = stride-2 max pool (symbol_googlenet.py get_symbol sequence)
_GOOGLENET_BLOCKS = [
    ("in3a", (64, 96, 128, 16, 32, 32)),
    ("in3b", (128, 128, 192, 32, 96, 64)),
    ("pool4", None),
    ("in4a", (192, 96, 208, 16, 48, 64)),
    ("in4b", (160, 112, 224, 24, 64, 64)),
    ("in4c", (128, 128, 256, 24, 64, 64)),
    ("in4d", (112, 144, 288, 32, 64, 64)),
    ("in4e", (256, 160, 320, 32, 128, 128)),
    ("pool5", None),
    ("in5a", (256, 160, 320, 32, 128, 128)),
    ("in5b", (384, 192, 384, 48, 128, 128)),
]


def googlenet(num_classes=1000, layout="NCHW"):
    """GoogLeNet / Inception v1 (symbol_googlenet.py): 1x1 + 3x3 + 5x5 +
    pool-proj branches, no batch norm."""
    concat_axis = -1 if layout == "NHWC" else 1
    x = mx_sym.Variable("data")
    x = _conv_relu(x, 64, (7, 7), "1", stride=(2, 2), pad=(3, 3),
                   layout=layout)
    x = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       pooling_convention="full", layout=layout,
                       name="pool_1")
    x = _conv_relu(x, 64, (1, 1), "2", layout=layout)
    x = _conv_relu(x, 192, (3, 3), "3", pad=(1, 1), layout=layout)
    x = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       pooling_convention="full", layout=layout,
                       name="pool_3")
    for name, cfg in _GOOGLENET_BLOCKS:
        if cfg is None:
            # legacy mshadow ceil convention keeps the reference's 7x7
            # map at the head (112->56->28->14->7)
            x = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2),
                               pool_type="max", pooling_convention="full",
                               layout=layout, name=name)
            continue
        n1, nr3, n3, nr5, n5, proj = cfg
        b1 = _conv_relu(x, n1, (1, 1), f"{name}_1x1", layout=layout)
        b3 = _conv_relu(x, nr3, (1, 1), f"{name}_3x3r", layout=layout)
        b3 = _conv_relu(b3, n3, (3, 3), f"{name}_3x3", pad=(1, 1),
                        layout=layout)
        b5 = _conv_relu(x, nr5, (1, 1), f"{name}_5x5r", layout=layout)
        b5 = _conv_relu(b5, n5, (5, 5), f"{name}_5x5", pad=(2, 2),
                        layout=layout)
        bp = mx_sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                            pool_type="max", layout=layout,
                            name=f"pool_{name}")
        bp = _conv_relu(bp, proj, (1, 1), f"{name}_proj", layout=layout)
        x = mx_sym.Concat(b1, b3, b5, bp, num_args=4, dim=concat_axis,
                          name=f"concat_{name}")
    x = mx_sym.Pooling(x, kernel=(7, 7), stride=(1, 1), pool_type="avg",
                       global_pool=True, layout=layout, name="global_pool")
    x = mx_sym.Flatten(x, name="flatten")
    x = mx_sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx_sym.SoftmaxOutput(x, name="softmax")


def inception_bn_small(num_classes=10, layout="NCHW", force_mirroring=False):
    """The CIFAR-10 "28-small" variant (the multi-GPU img/sec baseline,
    symbol_inception-bn-28-small.py); ``force_mirroring`` tags every
    activation for gradient-checkpoint recompute like the reference's
    mirror_attr."""
    from ..attribute import AttrScope

    concat_axis = -1 if layout == "NHWC" else 1
    scope = (AttrScope(force_mirroring="true") if force_mirroring
             else AttrScope())
    with scope:
        data = mx_sym.Variable("data")
        x = _conv_bn(data, 96, (3, 3), "1", pad=(1, 1), layout=layout)
        for name, (n1, n3) in _SMALL_BLOCKS:
            if n1 is None:   # downsample: stride-2 conv branch ++ max pool
                conv = _conv_bn(x, n3, (3, 3), f"{name}_ds", stride=(2, 2),
                                pad=(1, 1), layout=layout)
                pool = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2),
                                      pad=(1, 1), pool_type="max",
                                      layout=layout, name=f"pool_{name}")
                x = mx_sym.Concat(conv, pool, num_args=2, dim=concat_axis,
                                  name=f"concat_{name}")
            else:            # simple: 1x1 branch ++ 3x3 branch
                a = _conv_bn(x, n1, (1, 1), f"{name}_1x1", layout=layout)
                b = _conv_bn(x, n3, (3, 3), f"{name}_3x3", pad=(1, 1),
                             layout=layout)
                x = mx_sym.Concat(a, b, num_args=2, dim=concat_axis,
                                  name=f"concat_{name}")
        x = mx_sym.Pooling(x, kernel=(7, 7), pool_type="avg", layout=layout,
                           name="global_pool")
        x = mx_sym.Flatten(x, name="flatten1")
        x = mx_sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
        return mx_sym.SoftmaxOutput(x, name="softmax")
