"""VGG and AlexNet (reference example/image-classification/symbol_vgg.py,
symbol_alexnet.py) — the ImageNet epoch-time baseline models
(SURVEY.md §6: VGG bs=96/384 epoch table).

Config-table rebuild: the VGG conv trunk is a per-stage filter list
(11/13/16/19-layer variants) instead of unrolled symbol code; NHWC
layout supported for TPU.
"""

from .. import symbol as mx_sym

# convs per stage for each named depth; reference symbol_vgg.py is the
# 11-layer table
_VGG_CFG = {
    11: ([1, 1, 2, 2, 2], [64, 128, 256, 512, 512]),
    13: ([2, 2, 2, 2, 2], [64, 128, 256, 512, 512]),
    16: ([2, 2, 3, 3, 3], [64, 128, 256, 512, 512]),
    19: ([2, 2, 4, 4, 4], [64, 128, 256, 512, 512]),
}


def vgg(num_classes=1000, num_layers=11, batch_norm=False, layout="NCHW"):
    """VGG-style network; ``num_layers`` in {11, 13, 16, 19}."""
    if num_layers not in _VGG_CFG:
        raise ValueError(f"vgg: unsupported depth {num_layers}")
    counts, filters = _VGG_CFG[num_layers]
    bn_axis = -1 if layout == "NHWC" else 1

    x = mx_sym.Variable("data")
    for stage, (reps, nf) in enumerate(zip(counts, filters), start=1):
        for i in range(1, reps + 1):
            x = mx_sym.Convolution(x, kernel=(3, 3), pad=(1, 1),
                                   num_filter=nf, layout=layout,
                                   name=f"conv{stage}_{i}")
            if batch_norm:
                x = mx_sym.BatchNorm(x, fix_gamma=False, axis=bn_axis,
                                     name=f"bn{stage}_{i}")
            x = mx_sym.Activation(x, act_type="relu",
                                  name=f"relu{stage}_{i}")
        x = mx_sym.Pooling(x, pool_type="max", kernel=(2, 2), stride=(2, 2),
                           layout=layout, name=f"pool{stage}")

    x = mx_sym.Flatten(x, name="flatten")
    for i, fc_name in enumerate(("fc6", "fc7")):
        x = mx_sym.FullyConnected(x, num_hidden=4096, name=fc_name)
        x = mx_sym.Activation(x, act_type="relu", name=f"relu{6 + i}")
        x = mx_sym.Dropout(x, p=0.5, name=f"drop{6 + i}")
    x = mx_sym.FullyConnected(x, num_hidden=num_classes, name="fc8")
    return mx_sym.SoftmaxOutput(x, name="softmax")


def alexnet(num_classes=1000, layout="NCHW"):
    """AlexNet (symbol_alexnet.py): 5-conv trunk with LRN after the
    first two pools, 4096-wide classifier head."""
    x = mx_sym.Variable("data")
    trunk = [
        # (filters, kernel, stride, pad, pool?, lrn?)
        (96, (11, 11), (4, 4), (0, 0), True, True),
        (256, (5, 5), (1, 1), (2, 2), True, True),
        (384, (3, 3), (1, 1), (1, 1), False, False),
        (384, (3, 3), (1, 1), (1, 1), False, False),
        (256, (3, 3), (1, 1), (1, 1), True, False),
    ]
    for i, (nf, k, s, p, pool, lrn) in enumerate(trunk, start=1):
        x = mx_sym.Convolution(x, kernel=k, stride=s, pad=p, num_filter=nf,
                               layout=layout, name=f"conv{i}")
        x = mx_sym.Activation(x, act_type="relu", name=f"relu{i}")
        if pool:
            x = mx_sym.Pooling(x, kernel=(3, 3), stride=(2, 2),
                               pool_type="max", layout=layout,
                               name=f"pool{i}")
        if lrn:
            x = mx_sym.LRN(x, alpha=0.0001, beta=0.75, knorm=1, nsize=5,
                           name=f"norm{i}")
    x = mx_sym.Flatten(x, name="flatten")
    for i in (1, 2):
        x = mx_sym.FullyConnected(x, num_hidden=4096, name=f"fc{i}")
        x = mx_sym.Activation(x, act_type="relu", name=f"fcrelu{i}")
        x = mx_sym.Dropout(x, p=0.5, name=f"fcdrop{i}")
    x = mx_sym.FullyConnected(x, num_hidden=num_classes, name="fc3")
    return mx_sym.SoftmaxOutput(x, name="softmax")
