"""Model zoo: symbols for the BASELINE.json workloads
(reference example/image-classification/symbol_*.py, example/rnn)."""

from .lenet import get_symbol as lenet
from .mlp import get_symbol as mlp
from .resnet import get_symbol as resnet
from .lstm import lstm_unroll, lstm_cell, LSTMState, LSTMParam

__all__ = ["lenet", "mlp", "resnet", "lstm_unroll", "lstm_cell",
           "LSTMState", "LSTMParam"]
