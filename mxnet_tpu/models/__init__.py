"""Model zoo: symbols for the BASELINE.json workloads
(reference example/image-classification/symbol_*.py, example/rnn)."""

from .lenet import get_symbol as lenet
from .mlp import get_symbol as mlp
from .resnet import get_symbol as resnet
from .lstm import lstm_unroll, lstm_cell, LSTMState, LSTMParam
from .ssd import get_symbol as ssd
from .inception import inception_bn, inception_bn_small, googlenet
from .vgg import vgg, alexnet
from .transformer import gpt
from .generate import gpt_decode_config, gpt_generate

__all__ = ["lenet", "mlp", "resnet", "lstm_unroll", "lstm_cell",
           "LSTMState", "LSTMParam", "ssd",
           "inception_bn", "inception_bn_small", "googlenet", "vgg", "alexnet",
           "gpt", "gpt_generate", "gpt_decode_config"]
