"""Explicitly-unrolled LSTM language model
(reference example/rnn/lstm.py:17-40 lstm cell, lstm_unroll).

This is the bucketing-LM symbol (BASELINE config 3's explicit-unroll
variant); the fused scan-based RNN op covers the cuDNN-RNN path.
"""

from __future__ import annotations

from collections import namedtuple

from .. import symbol as mx_sym

LSTMState = namedtuple("LSTMState", ["c", "h"])
LSTMParam = namedtuple("LSTMParam", ["i2h_weight", "i2h_bias",
                                     "h2h_weight", "h2h_bias"])


def lstm_cell(num_hidden, indata, prev_state, param, seqidx, layeridx,
              dropout=0.0):
    """One LSTM step (reference example/rnn/lstm.py:17-40)."""
    if dropout > 0.0:
        indata = mx_sym.Dropout(indata, p=dropout)
    i2h = mx_sym.FullyConnected(indata, weight=param.i2h_weight,
                                bias=param.i2h_bias, num_hidden=num_hidden * 4,
                                name=f"t{seqidx}_l{layeridx}_i2h")
    h2h = mx_sym.FullyConnected(prev_state.h, weight=param.h2h_weight,
                                bias=param.h2h_bias, num_hidden=num_hidden * 4,
                                name=f"t{seqidx}_l{layeridx}_h2h")
    gates = i2h + h2h
    slice_gates = mx_sym.SliceChannel(gates, num_outputs=4,
                                      name=f"t{seqidx}_l{layeridx}_slice")
    in_gate = mx_sym.Activation(slice_gates[0], act_type="sigmoid")
    in_transform = mx_sym.Activation(slice_gates[1], act_type="tanh")
    forget_gate = mx_sym.Activation(slice_gates[2], act_type="sigmoid")
    out_gate = mx_sym.Activation(slice_gates[3], act_type="sigmoid")
    next_c = (forget_gate * prev_state.c) + (in_gate * in_transform)
    next_h = out_gate * mx_sym.Activation(next_c, act_type="tanh")
    return LSTMState(c=next_c, h=next_h)


def lstm_unroll(num_lstm_layer, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0):
    """Unrolled LSTM LM over a padded sequence
    (reference example/rnn/lstm.py lstm_unroll)."""
    embed_weight = mx_sym.Variable("embed_weight")
    cls_weight = mx_sym.Variable("cls_weight")
    cls_bias = mx_sym.Variable("cls_bias")
    param_cells = []
    last_states = []
    for i in range(num_lstm_layer):
        param_cells.append(LSTMParam(
            i2h_weight=mx_sym.Variable(f"l{i}_i2h_weight"),
            i2h_bias=mx_sym.Variable(f"l{i}_i2h_bias"),
            h2h_weight=mx_sym.Variable(f"l{i}_h2h_weight"),
            h2h_bias=mx_sym.Variable(f"l{i}_h2h_bias")))
        last_states.append(LSTMState(
            c=mx_sym.Variable(f"l{i}_init_c"),
            h=mx_sym.Variable(f"l{i}_init_h")))

    data = mx_sym.Variable("data")
    label = mx_sym.Variable("softmax_label")
    embed = mx_sym.Embedding(data, weight=embed_weight, input_dim=input_size,
                             output_dim=num_embed, name="embed")
    wordvec = mx_sym.SliceChannel(embed, num_outputs=seq_len, axis=1,
                                  squeeze_axis=True)

    hidden_all = []
    for seqidx in range(seq_len):
        hidden = wordvec[seqidx]
        for i in range(num_lstm_layer):
            next_state = lstm_cell(num_hidden, indata=hidden,
                                   prev_state=last_states[i],
                                   param=param_cells[i], seqidx=seqidx,
                                   layeridx=i,
                                   dropout=dropout if i > 0 else 0.0)
            hidden = next_state.h
            last_states[i] = next_state
        if dropout > 0.0:
            hidden = mx_sym.Dropout(hidden, p=dropout)
        hidden_all.append(hidden)

    hidden_concat = mx_sym.Concat(*hidden_all, num_args=len(hidden_all), dim=0)
    pred = mx_sym.FullyConnected(hidden_concat, weight=cls_weight,
                                 bias=cls_bias, num_hidden=num_label,
                                 name="pred")
    label_t = mx_sym.transpose(label)
    label_flat = mx_sym.Reshape(label_t, shape=(-1,))
    return mx_sym.SoftmaxOutput(pred, label_flat, name="softmax")
