"""FeedForward estimator + shared training-loop plumbing.

Rebuild of python/mxnet/model.py: ``_create_kvstore`` (model.py:39-76),
the kvstore update paths with per-key priority (−index) for
comm/compute overlap (model.py:87-115), two-artifact checkpointing
(save/load_checkpoint, model.py:318-384) and the sklearn-style
``FeedForward`` estimator (model.py:386+) built on the Module API.
"""

from __future__ import annotations

import logging
import os
import threading

import numpy as np

from . import context as ctx_mod
from . import io as io_mod
from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError
from .initializer import Uniform
from .kvstore import KVStore
from .kvstore import create as _kv_create

__all__ = ["FeedForward", "save_checkpoint", "load_checkpoint"]

BASE_ESTIMATOR = object


def _create_kvstore(kvstore, num_device, arg_params):
    """Select kvstore + update placement (reference model.py:39-76)."""
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = _kv_create(kvstore)
            if kvstore == "local":
                max_size = max(int(np.prod(p.shape)) for p in arg_params.values())
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def _initialize_kvstore(kvstore, param_arrays, arg_params, param_names,
                        update_on_kvstore):
    for idx, param_on_devs in enumerate(param_arrays):
        kvstore.init(idx, arg_params[param_names[idx]])
        if update_on_kvstore:
            kvstore.pull(idx, param_on_devs, priority=-idx)


def _update_params_on_kvstore(param_arrays, grad_arrays, kvstore):
    """push grad / pull weight per key, priority −index so layer-k comm
    overlaps layer-(k−1) compute (reference model.py:87-97)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        kvstore.push(index, grad_list, priority=-index)
        kvstore.pull(index, arg_list, priority=-index)


def _update_params(param_arrays, grad_arrays, updater, num_device, kvstore=None):
    """Aggregate grads (optionally via kvstore) then run the local updater
    per device copy (reference model.py:98-115)."""
    for index, pair in enumerate(zip(param_arrays, grad_arrays)):
        arg_list, grad_list = pair
        if grad_list[0] is None:
            continue
        if kvstore:
            kvstore.push(index, grad_list, priority=-index)
            kvstore.pull(index, grad_list, priority=-index)
        elif len(grad_list) > 1:
            # sum gradients ONCE in place of kvstore local-reduce and
            # feed the reduced grad straight to each device's updater —
            # no write-back copy into every grad buffer (the old path
            # materialized `total` then copied it N times)
            total = grad_list[0]
            for g in grad_list[1:]:
                total = total + g.as_in_context(total.context)
            grad_list = [total if g.context == total.context
                         else total.as_in_context(g.context)
                         for g in grad_list]
        for k, (w, g) in enumerate(zip(arg_list, grad_list)):
            updater(index * num_device + k, g, w)


_async_saves = []
_async_errors = []
_async_saves_lock = threading.Lock()


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    async_save=False, snapshot_owned=False):
    """Two-artifact checkpoint: ``prefix-symbol.json`` +
    ``prefix-####.params`` (reference model.py:318-347).

    ``async_save`` gives orbax-style semantics: the device->host snapshot
    is taken synchronously (the checkpoint reflects this exact step), the
    disk write runs on a background thread into a temp file that is
    atomically renamed on completion, so training never waits on storage
    and a crash mid-write cannot leave a torn checkpoint.  Call
    ``wait_checkpoints()`` (or exit the process cleanly) before relying
    on the file.

    ``snapshot_owned=True`` declares the passed arrays are fresh copies
    the caller will not mutate (e.g. ShardedTrainer.get_params output),
    skipping the defensive per-array copy — avoids a second full host
    copy of large models."""
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in aux_params.items()})
    param_name = f"{prefix}-{epoch:04d}.params"
    if not async_save:
        nd.save(param_name, save_dict)
        logging.info('Saved checkpoint to "%s"', param_name)
        return
    # synchronous snapshot: values are pinned to host numpy NOW (copy=True
    # — np.asarray would alias caller-owned numpy arrays that training
    # keeps mutating in place), so later updates can't leak into the file
    if snapshot_owned:
        snapshot = save_dict
    else:
        snapshot = {k: (v.asnumpy() if hasattr(v, "asnumpy")
                        else np.array(v, copy=True))
                    for k, v in save_dict.items()}

    stage_async_write(
        param_name, lambda tmp: nd.save(tmp, snapshot),
        on_done=lambda: logging.info('Saved checkpoint (async) to "%s"',
                                     param_name))


def stage_async_write(path, writer, on_done=None):
    """Stage an ATOMIC background file write tracked by
    :func:`wait_checkpoints`: ``writer(tmp_path)`` produces the file,
    which is renamed over ``path`` only on success; failures are
    recorded per path and re-raised at wait time.  Shared by
    FeedForward/Module checkpoints and ShardedTrainer checkpoints."""

    def _write():
        # pid + thread id: two concurrent in-process saves to the same
        # path must not share (and tear) a temp file
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        try:
            writer(tmp)
            os.replace(tmp, path)
            if on_done is not None:
                on_done()
        except BaseException as e:
            try:
                os.remove(tmp)
            except OSError:
                pass
            with _async_saves_lock:
                _async_errors.append((path, e))
            # surfaced by wait_checkpoints(); re-raising here would only
            # trip threading.excepthook as an unhandled thread error
            logging.warning("async checkpoint write failed for %r: %r",
                            path, e)

    t = threading.Thread(target=_write, daemon=False,
                         name=f"ckpt-{os.path.basename(path)}")
    t.start()  # start BEFORE registering: a pre-start thread is not
    with _async_saves_lock:  # alive and a concurrent prune would drop it
        _async_saves[:] = [x for x in _async_saves if x.is_alive()]
        _async_saves.append(t)


def wait_checkpoints():
    """Block until all in-flight async checkpoint writes are on disk.
    Raises the first failure (disk full etc.) instead of silently
    reporting success over a missing epoch."""
    with _async_saves_lock:
        pending = list(_async_saves)
        _async_saves.clear()
    for t in pending:
        t.join()
    with _async_saves_lock:
        errors, _async_errors[:] = list(_async_errors), []
    if errors:
        name, err = errors[0]
        raise MXNetError(
            f"async checkpoint write failed for {name!r}: {err!r}") from err


def load_checkpoint(prefix, epoch):
    """Load (symbol, arg_params, aux_params) (reference model.py:350-384)."""
    symbol = sym_mod.load(f"{prefix}-symbol.json")
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


class FeedForward(BASE_ESTIMATOR):
    """sklearn-style estimator (reference model.py:386 FeedForward).

    Implemented over the Module API (the reference's own successor path);
    keeps fit/predict/score/save/load and ctor surface.
    """

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=Uniform(0.01), numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        self.symbol = symbol
        if ctx is None:
            ctx = [ctx_mod.current_context()]
        elif isinstance(ctx, ctx_mod.Context):
            ctx = [ctx]
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    # -- helpers -----------------------------------------------------------
    def _init_iter(self, X, y, is_train):
        if isinstance(X, io_mod.DataIter):
            return X
        X = np.asarray(X)
        if y is not None:
            y = np.asarray(y)
        batch_size = min(self.numpy_batch_size, X.shape[0])
        if is_train:
            if y is None:
                raise ValueError("y is required for training")
            return io_mod.NDArrayIter(X, y, batch_size, shuffle=True,
                                      last_batch_handle="roll_over")
        return io_mod.NDArrayIter(X, y, batch_size, shuffle=False)

    def _get_module(self, data):
        from .module import Module

        data_names = [d[0] for d in data.provide_data]
        label_names = [l[0] for l in data.provide_label]
        return Module(self.symbol, data_names=data_names,
                      label_names=label_names, context=self.ctx)

    # -- public API --------------------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        data = self._init_iter(X, y, is_train=True)
        if eval_data is not None and not isinstance(eval_data, io_mod.DataIter):
            ex, ey = eval_data
            eval_data = self._init_iter(np.asarray(ex), np.asarray(ey), False)
        self._module = self._get_module(data)
        opt_params = dict(self.kwargs)
        self._module.fit(data, eval_data=eval_data, eval_metric=eval_metric,
                         epoch_end_callback=epoch_end_callback,
                         batch_end_callback=batch_end_callback,
                         kvstore=kvstore, optimizer=self.optimizer,
                         optimizer_params=opt_params,
                         eval_end_callback=eval_end_callback,
                         eval_batch_end_callback=eval_batch_end_callback,
                         initializer=self.initializer,
                         arg_params=self.arg_params, aux_params=self.aux_params,
                         allow_missing=True, begin_epoch=self.begin_epoch,
                         num_epoch=self.num_epoch, monitor=monitor)
        self.arg_params, self.aux_params = self._module.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        data = self._init_iter(X, None, is_train=False)
        if self._module is None or not self._module.binded:
            self._module = self._get_module(data)
            self._module.bind(data.provide_data, data.provide_label,
                              for_training=False)
            self._module.set_params(self.arg_params, self.aux_params or {},
                                    allow_missing=True)
        out = self._module.predict(data, num_batch=num_batch, reset=reset)
        if isinstance(out, list):
            return [o.asnumpy() for o in out]
        return out.asnumpy()

    def score(self, X, y=None, eval_metric="acc", num_batch=None, reset=True):
        data = self._init_iter(X, y, is_train=False)
        if self._module is None or not self._module.binded:
            self._module = self._get_module(data)
            self._module.bind(data.provide_data, data.provide_label,
                              for_training=False)
            self._module.set_params(self.arg_params, self.aux_params or {},
                                    allow_missing=True)
        res = self._module.score(data, eval_metric, num_batch=num_batch,
                                 reset=reset)
        return res[0][1]

    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch
        save_checkpoint(prefix, epoch, self.symbol, self.arg_params or {},
                        self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch, **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=Uniform(0.01), eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
