"""Learning-rate schedulers (rebuild of python/mxnet/lr_scheduler.py)."""

from __future__ import annotations

import logging

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every ``step`` updates (lr_scheduler.py:36)."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be at least 1")
        if factor > 1.0:
            raise ValueError("factor must be no more than 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("Update[%d]: lr reached stop factor %.5e",
                             num_update, self.base_lr)
            else:
                logging.info("Update[%d]: change lr to %.5e", num_update, self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at given update milestones (lr_scheduler.py:85)."""

    def __init__(self, step, factor=1.0):
        super().__init__()
        if not all(step[i] < step[i + 1] for i in range(len(step) - 1)):
            raise ValueError("steps must be increasing")
        if step[0] < 1:
            raise ValueError("steps must be at least 1")
        self.step = list(step)
        self.cur_step_ind = 0
        self.factor = factor

    def __call__(self, num_update):
        while (self.cur_step_ind <= len(self.step) - 1
               and num_update > self.step[self.cur_step_ind]):
            self.base_lr *= self.factor
            self.cur_step_ind += 1
            logging.info("Update[%d]: change lr to %.5e", num_update, self.base_lr)
        return self.base_lr
