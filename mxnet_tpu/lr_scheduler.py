"""Learning-rate schedulers (rebuild of python/mxnet/lr_scheduler.py)."""

from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update: int) -> float:
        raise NotImplementedError


class FactorScheduler(LRScheduler):
    """lr *= factor every ``step`` updates (lr_scheduler.py:36)."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("step must be at least 1")
        if factor > 1.0:
            raise ValueError("factor must be no more than 1")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self.count = 0

    def __call__(self, num_update):
        while num_update > self.count + self.step:
            self.count += self.step
            self.base_lr *= self.factor
            if self.base_lr < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("Update[%d]: lr reached stop factor %.5e",
                             num_update, self.base_lr)
            else:
                logging.info("Update[%d]: change lr to %.5e", num_update, self.base_lr)
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """lr *= factor at given update milestones (lr_scheduler.py:85)."""

    def __init__(self, step, factor=1.0):
        super().__init__()
        if not all(step[i] < step[i + 1] for i in range(len(step) - 1)):
            raise ValueError("steps must be increasing")
        if step[0] < 1:
            raise ValueError("steps must be at least 1")
        self.step = list(step)
        self.cur_step_ind = 0
        self.factor = factor

    def __call__(self, num_update):
        while (self.cur_step_ind <= len(self.step) - 1
               and num_update > self.step[self.cur_step_ind]):
            self.base_lr *= self.factor
            self.cur_step_ind += 1
            logging.info("Update[%d]: change lr to %.5e", num_update, self.base_lr)
        return self.base_lr


class PolyScheduler(LRScheduler):
    """Polynomial decay from base_lr to ``final_lr`` over
    ``max_update`` steps (beyond the 2016 reference; the classic
    ImageNet alternative to step decay), with optional linear warmup."""

    def __init__(self, max_update, power=2.0, final_lr=0.0,
                 warmup_steps=0, warmup_begin_lr=0.0):
        super().__init__()
        if max_update < 1:
            raise ValueError("max_update must be at least 1")
        self.max_update = max_update
        self.power = power
        self.final_lr = final_lr
        self.warmup_steps = warmup_steps
        self.warmup_begin_lr = warmup_begin_lr

    def _warmup(self, num_update):
        return (self.warmup_begin_lr
                + (self.base_lr - self.warmup_begin_lr)
                * num_update / self.warmup_steps)

    def _progress(self, num_update):
        """Post-warmup decay fraction in [0, 1] (clamped past max)."""
        return min(
            (num_update - self.warmup_steps)
            / max(self.max_update - self.warmup_steps, 1), 1.0)

    def _decay(self, frac):
        """Decay weight in [0, 1] at post-warmup progress ``frac``;
        subclasses override this single hook."""
        return (1.0 - frac) ** self.power

    def __call__(self, num_update):
        if num_update < self.warmup_steps:
            return self._warmup(num_update)
        return (self.final_lr + (self.base_lr - self.final_lr)
                * self._decay(self._progress(num_update)))


class CosineScheduler(PolyScheduler):
    """Cosine decay from base_lr to ``final_lr`` over ``max_update``
    steps with optional linear warmup (beyond the 2016 reference; the
    standard TPU-era large-batch schedule, paired with LARS/LAMB)."""

    def __init__(self, max_update, final_lr=0.0, warmup_steps=0,
                 warmup_begin_lr=0.0):
        super().__init__(max_update, final_lr=final_lr,
                         warmup_steps=warmup_steps,
                         warmup_begin_lr=warmup_begin_lr)

    def _decay(self, frac):
        return 0.5 * (1.0 + math.cos(math.pi * frac))
