"""Notebook utilities (rebuild of python/mxnet/notebook/)."""

from . import callback
