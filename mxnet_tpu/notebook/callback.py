"""Training-visualization callbacks for notebooks
(rebuild of python/mxnet/notebook/callback.py).

The reference renders live bokeh charts from batch/epoch callbacks and
logs metric history into pandas frames.  Same surface here, with the
same graceful degradation the reference practices (its import guards):
history always accumulates; ``PandasLogger`` hands back DataFrames when
pandas is importable and plain dict-of-lists otherwise; the live chart
draws with matplotlib when available and stays silent headless.
"""

from __future__ import annotations

import time


def _metric_pairs(eval_metric):
    names, values = eval_metric.get()
    if not isinstance(names, (list, tuple)):
        names, values = [names], [values]
    return list(zip(names, values))


class MetricHistory:
    """Accumulates (epoch, batch, metric) rows from the standard
    batch/epoch callback protocol; base for the loggers/charts."""

    def __init__(self, frequent=50):
        self.frequent = frequent
        self.train = []      # rows: {epoch, batch, elapsed, <metrics...>}
        self.eval = []       # rows: {epoch, elapsed, <metrics...>}
        # perf_counter: elapsed must be monotonic (an NTP slew under
        # time.time() would bend the learning-curve x axis) — the same
        # fix Speedometer and the fit loop already carry
        self._start = time.perf_counter()

    # -- callback protocol --------------------------------------------------
    def __call__(self, param):
        self.train_cb(param)

    def train_cb(self, param):
        if param.nbatch % self.frequent != 0:
            return
        row = {"epoch": param.epoch, "batch": param.nbatch,
               "elapsed": time.perf_counter() - self._start}
        row.update(_metric_pairs(param.eval_metric))
        self.train.append(row)
        self._on_update()

    def epoch_cb(self, epoch=None, symbol=None, arg_params=None,
                 aux_params=None):
        self._on_update()

    def eval_cb(self, param):
        row = {"epoch": param.epoch,
               "elapsed": time.perf_counter() - self._start}
        row.update(_metric_pairs(param.eval_metric))
        self.eval.append(row)
        self._on_update()

    def _on_update(self):
        pass


class PandasLogger(MetricHistory):
    """Metric history as pandas DataFrames (reference PandasLogger).

    ``train_df`` / ``eval_df`` return DataFrames when pandas is
    available, else the raw list of row dicts.
    """

    def _frame(self, rows):
        try:  # lazy: pandas costs ~0.5s to import and is optional
            import pandas as pd
        except ImportError:
            return rows
        return pd.DataFrame(rows)

    @property
    def train_df(self):
        return self._frame(self.train)

    @property
    def eval_df(self):
        return self._frame(self.eval)


class LiveLearningCurve(MetricHistory):
    """Live-updating learning curve (reference LiveLearningCurve, bokeh
    -> matplotlib here).  Creates the figure lazily on first update so
    constructing the callback is safe on headless machines."""

    def __init__(self, metric_name="accuracy", frequent=50):
        super().__init__(frequent=frequent)
        self.metric_name = metric_name
        self._fig = None
        self._disabled = False

    def _on_update(self):
        if self._disabled:
            return
        try:
            import matplotlib
            import matplotlib.pyplot as plt
        except ImportError:
            self._disabled = True
            return
        xs, ys = [], []
        for row in self.train:
            if self.metric_name in row:
                xs.append(row["elapsed"])
                ys.append(row[self.metric_name])
        if not xs:
            return
        if self._fig is None:
            self._fig, self._ax = plt.subplots(figsize=(6, 3))
            self._plt = plt
        self._ax.clear()
        self._ax.plot(xs, ys, label=f"train {self.metric_name}")
        ex = [r["elapsed"] for r in self.eval if self.metric_name in r]
        ey = [r[self.metric_name] for r in self.eval if self.metric_name in r]
        if ex:
            self._ax.plot(ex, ey, label=f"eval {self.metric_name}")
        self._ax.set_xlabel("seconds")
        self._ax.set_ylabel(self.metric_name)
        self._ax.legend(loc="lower right")
        try:  # live redraw inside IPython; a plain script just keeps history
            from IPython import display

            display.clear_output(wait=True)
            display.display(self._fig)
        # mxtpu-lint: disable=swallowed-exception (plain-script mode:
        # no IPython display — the curve history is still kept)
        except Exception:
            pass

    def savefig(self, path):
        self._on_update()
        if self._fig is not None:
            self._fig.savefig(path)

    def close(self):
        """Release the figure from pyplot's global registry."""
        if self._fig is not None:
            self._plt.close(self._fig)
            self._fig = None

    def __del__(self):
        try:
            self.close()
        # mxtpu-lint: disable=swallowed-exception (interpreter-teardown
        # guard: pyplot may already be torn down under us)
        except Exception:
            pass
