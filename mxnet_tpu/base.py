"""Base utilities: dtype mapping, errors, misc helpers.

TPU-native rebuild of the role played by the reference's
``python/mxnet/base.py`` (ctypes loader / handle types) and
``include/mxnet/base.h``.  There is no C library handle layer here: the
"backend" is JAX/XLA, so this module only carries the shared dtype table,
exception types and small helpers used across the package.
"""

from __future__ import annotations

import numpy as np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "np_dtype",
    "dtype_name",
    "DTYPE_NAMES",
]


class MXNetError(Exception):
    """Error raised by the framework (parity with reference base.py:39)."""


string_types = (str,)
numeric_types = (float, int, np.generic)

# Canonical dtype table.  The reference supports fp16/32/64, uint8, int32
# (mshadow type switch); we add bfloat16 as the TPU-native half type and
# int64/bool for completeness.
_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def _bfloat16():
    import jax.numpy as jnp

    return jnp.bfloat16


def np_dtype(dtype):
    """Normalize a dtype-like (string, np.dtype, python type) to a numpy dtype.

    ``bfloat16`` is resolved through jax (ml_dtypes) since numpy has no
    native bfloat16.
    """
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return np.dtype(_bfloat16())
        if dtype in _DTYPE_ALIASES:
            return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype-like."""
    return np_dtype(dtype).name


DTYPE_NAMES = tuple(_DTYPE_ALIASES) + ("bfloat16",)


def check_call(ret):
    """No-op kept for API familiarity with the reference's ctypes layer."""
    return ret
