"""Base utilities: dtype mapping, errors, misc helpers.

TPU-native rebuild of the role played by the reference's
``python/mxnet/base.py`` (ctypes loader / handle types) and
``include/mxnet/base.h``.  There is no C library handle layer here: the
"backend" is JAX/XLA, so this module only carries the shared dtype table,
exception types and small helpers used across the package.
"""

from __future__ import annotations

import ctypes

import numpy as np

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "np_dtype",
    "dtype_name",
    "DTYPE_NAMES",
    "c_array",
    "c_str",
    "ctypes2buffer",
    "ctypes2docstring",
    "ctypes2numpy_shared",
    "env_flag",
    "env_int",
    "env_float",
]


def env_flag(name, default=True):
    """Boolean MXTPU_* knob: one parse for every call site so accepted
    spellings can't drift between features."""
    import os

    value = os.environ.get(name)
    if value is None or value == "":
        return default
    return value not in ("0", "false", "False", "FALSE", "no", "off")


def env_int(name, default):
    """Integer MXTPU_* knob; a malformed value falls back to the
    default instead of crashing the caller's hot path."""
    import os

    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def env_float(name, default):
    """Float MXTPU_* knob (timeouts, rates); malformed values fall back
    to the default like :func:`env_int`."""
    import os

    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


def c_array(ctype, values):
    """ctypes array from a python sequence (reference base.py c_array)
    — used by C-ABI consumers of this package (libinfo/c_api_bridge)."""
    return (ctype * len(values))(*values)


def c_str(string):
    """ctypes char pointer from a python string (reference base.py)."""
    return ctypes.c_char_p(string.encode("utf-8"))


def ctypes2buffer(cptr, length):
    """Copy a ctypes char pointer into a bytearray (reference
    base.py ctypes2buffer)."""
    if not isinstance(cptr, ctypes.POINTER(ctypes.c_char)):
        raise TypeError("expected char pointer")
    res = bytearray(length)
    rptr = (ctypes.c_char * length).from_buffer(res)
    if not ctypes.memmove(rptr, cptr, length):
        raise RuntimeError("memmove failed")
    return res


def ctypes2docstring(num_args, arg_names, arg_types, arg_descs,
                     remove_dup=True):
    """Render a parameter docstring from C-API registry metadata
    (reference base.py ctypes2docstring) — the generator thin frontends
    use when building docs from runtime-discovered op signatures."""
    param_keys = set()
    param_str = []
    for i in range(num_args.value if hasattr(num_args, "value")
                   else num_args):
        key = (arg_names[i].decode() if isinstance(arg_names[i], bytes)
               else arg_names[i])
        if key in param_keys and remove_dup:
            continue
        param_keys.add(key)
        atype = (arg_types[i].decode() if isinstance(arg_types[i], bytes)
                 else arg_types[i])
        desc = (arg_descs[i].decode() if isinstance(arg_descs[i], bytes)
                else arg_descs[i])
        ret = f"{key} : {atype}"
        if desc:
            ret += f"\n    {desc}"
        param_str.append(ret)
    return "Parameters\n----------\n" + "\n".join(param_str) + "\n"


def ctypes2numpy_shared(cptr, shape):
    """Zero-copy numpy view over ctypes float memory (reference
    base.py ctypes2numpy_shared)."""
    if not isinstance(cptr, ctypes.POINTER(ctypes.c_float)):
        raise TypeError("expected float pointer")
    size = 1
    for s in shape:
        size *= s
    dbuffer = (ctypes.c_float * size).from_address(
        ctypes.addressof(cptr.contents))
    return np.frombuffer(dbuffer, dtype=np.float32).reshape(shape)


class MXNetError(Exception):
    """Error raised by the framework (parity with reference base.py:39)."""


string_types = (str,)
numeric_types = (float, int, np.generic)

# Canonical dtype table.  The reference supports fp16/32/64, uint8, int32
# (mshadow type switch); we add bfloat16 as the TPU-native half type and
# int64/bool for completeness.
_DTYPE_ALIASES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "uint8": np.uint8,
    "int8": np.int8,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}


def _bfloat16():
    import jax.numpy as jnp

    return jnp.bfloat16


def np_dtype(dtype):
    """Normalize a dtype-like (string, np.dtype, python type) to a numpy dtype.

    ``bfloat16`` is resolved through jax (ml_dtypes) since numpy has no
    native bfloat16.
    """
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return np.dtype(_bfloat16())
        if dtype in _DTYPE_ALIASES:
            return np.dtype(_DTYPE_ALIASES[dtype])
    return np.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical string name for a dtype-like."""
    return np_dtype(dtype).name


DTYPE_NAMES = tuple(_DTYPE_ALIASES) + ("bfloat16",)


def check_call(ret):
    """No-op kept for API familiarity with the reference's ctypes layer."""
    return ret
