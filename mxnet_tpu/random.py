"""Global PRNG state for imperative sampling.

Rebuild of python/mxnet/random.py (seed + samplers).  The reference keeps
per-device mshadow::Random resources seeded via ``MXRandomSeed``; here a
single functional JAX key chain is split per imperative call, and
executors fork their own keys at bind time (deterministic given the seed).
"""

from __future__ import annotations

import threading

import jax
import numpy as np

__all__ = ["seed", "next_key", "uniform", "normal"]

_state = threading.local()


def _get_key():
    if not hasattr(_state, "key"):
        _state.key = jax.random.PRNGKey(0)
    return _state.key


def seed(seed_state: int):
    """Seed every framework RNG.  PROCESS-GLOBAL like the reference
    (mx.random.seed seeds the global mshadow RNGs its initializers draw
    from): covers this thread's JAX key chain (imperative samplers,
    executor/trainer key forks) AND numpy's process-wide generator (the
    initializer zoo), so one call makes init + training reproducible.
    Threads wanting independent chains should seed with distinct values
    and not interleave initializer construction."""
    _state.key = jax.random.PRNGKey(int(seed_state))
    np.random.seed(int(seed_state) & 0xFFFFFFFF)
    # per-context RandomResource chains (mx.resource.request("random"))
    # reseed too — the reference's MXRandomSeed hits exactly those
    from . import resource as _resource

    _resource.seed(seed_state)


def next_key():
    """Split and return a fresh key from the global chain."""
    key, sub = jax.random.split(_get_key())
    _state.key = key
    return sub


def uniform(low=0, high=1, shape=None, ctx=None, out=None):
    from . import ndarray as nd

    return nd._sample_uniform(low=low, high=high, shape=shape or (1,), ctx=ctx, out=out)


def normal(loc=0, scale=1, shape=None, ctx=None, out=None):
    from . import ndarray as nd

    return nd._sample_normal(loc=loc, scale=scale, shape=shape or (1,), ctx=ctx, out=out)
