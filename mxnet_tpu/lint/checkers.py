"""The mxtpu-lint checker suite.

Every checker here is grounded in a bug class this repo actually
shipped and re-fixed by hand across PRs 2-6 (see
docs/how_to/static_analysis.md for the before/after gallery):

  wall-clock             time.time() where perf_counter/monotonic is
                         required (PR 2/3/4 each converted stragglers)
  host-sync              float()/bool()/.item()/np.asarray on device
                         values inside fit/serve step loops (PR 3's
                         dispatch-count work was exactly this hunt)
  jit-cache-capture      module caches / lru_cache keying compiled
                         programs by object identity or capturing
                         engines (the _STEP_CACHE rule from PR 6)
  use-after-donate       reading a buffer after passing it to a
                         donate_argnums jit — runs fine on CPU (XLA
                         ignores donation there), corrupts on TPU
  env-discipline         MXTPU_* reads that bypass base.env_flag /
                         env_int / env_float, or undocumented vars
                         (subsumes tools/check_env_docs.py)
  unlocked-shared-state  mutation of a ``# guarded-by: <lock>``
                         attribute outside ``with self.<lock>``
  swallowed-exception    bare/broad except whose body is only
                         pass/continue — failures must count or log

Checkers are AST + comment based (see core.SourceFile); they never
import the code under analysis.
"""

from __future__ import annotations

import ast

from .core import register

__all__ = []  # programmatic access goes through core.all_checkers()


# -- shared AST helpers -------------------------------------------------------
def dotted(node):
    """Best-effort dotted name for Name/Attribute chains:
    ``self._cache_k`` -> "self._cache_k", ``np.asarray`` ->
    "np.asarray".  None for anything not a pure chain."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node):
    """Dotted name of a Call's callee, or None."""
    return dotted(node.func) if isinstance(node, ast.Call) else None


def contains(node, predicate):
    return any(predicate(n) for n in ast.walk(node))


def _const_ints(node):
    """Literal ints inside a tuple/list/int constant, else None."""
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for elt in node.elts:
            if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                out.append(elt.value)
            else:
                return None
        return out
    return None


def donated_argnums(call):
    """Donated positions of a ``jax.jit(...)`` call, or None when the
    call is not a jit / donates nothing / is statically unresolvable.

    Resolves literal tuples and the repo's ``_donate(i, j)`` guard
    (donation on TPU only — which is exactly why a use-after-donate
    survives every CPU test run)."""
    if call_name(call) not in ("jax.jit", "jit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        ints = _const_ints(kw.value)
        if ints:
            return ints
        if isinstance(kw.value, ast.Call) \
                and (call_name(kw.value) or "").endswith("_donate"):
            ints = [a.value for a in kw.value.args
                    if isinstance(a, ast.Constant)
                    and isinstance(a.value, int)]
            return ints or None
        return None
    return None


def functions_of(tree):
    """[(qualname, classname_or_None, node)] for every def in a
    module, including methods (qualname ``Class.method``).  Nested
    defs inside functions are skipped — in this codebase those are
    overwhelmingly traced jax closures, not host code."""
    out = []

    def visit(body, cls):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{cls}.{node.name}" if cls else node.name
                out.append((qn, cls, node))
            elif isinstance(node, ast.ClassDef):
                visit(node.body, node.name)

    visit(tree.body, None)
    return out


def walk_host_stmts(fn_node):
    """Walk a function's statements, skipping nested function/lambda
    bodies (traced-jax closure code is not host code)."""
    for stmt in fn_node.body:
        yield from _walk_skip_defs(stmt)


def _walk_skip_defs(node):
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield from _walk_skip_defs(child)


class Checker:
    id = None
    doc = ""

    def check(self, sf, ctx):
        raise NotImplementedError


# -- wall-clock ---------------------------------------------------------------
@register
class WallClockChecker(Checker):
    id = "wall-clock"
    doc = ("time.time() is wall-clock: NTP slews/steps make it "
           "non-monotonic, so elapsed-time math and deadlines computed "
           "from it can jump backwards. Use time.perf_counter() for "
           "durations, time.monotonic() for deadlines/rate limits; "
           "suppress with a reason only where a real timestamp is "
           "required (log records, filenames, comparisons against "
           "filesystem mtimes).")

    def check(self, sf, ctx):
        for node in ast.walk(sf.tree):
            if call_name(node) == "time.time":
                yield sf.finding(
                    self.id, node,
                    "time.time() — use perf_counter() (durations) or "
                    "monotonic() (deadlines); if a wall-clock timestamp "
                    "is semantically required, suppress with the reason")


# -- host-sync ----------------------------------------------------------------
_SYNC_ATTRS = {"item", "asnumpy", "tolist", "block_until_ready"}
_SYNC_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
               "jax.device_get"}


@register
class HostSyncChecker(Checker):
    id = "host-sync"
    doc = ("A float()/bool()/.item()/np.asarray/jax.device_get on a "
           "jax value blocks the host until the device catches up — "
           "inside a fit/serve step loop that stall serializes "
           "dispatch and shows up directly in steps/sec. Entry points "
           "are seeded with @hot_path (mxnet_tpu.lint.hot_path); the "
           "checker walks same-module calls reachable from them. "
           "Deliberate sync points (returning sampled tokens to the "
           "scheduler, an opt-in watchdog) carry suppressions naming "
           "the contract.")

    def check(self, sf, ctx):
        funcs = functions_of(sf.tree)
        by_qual = {qn: node for qn, _, node in funcs}
        hot = set()
        for qn, _, node in funcs:
            for dec in node.decorator_list:
                name = dotted(dec) or dotted(getattr(dec, "func", None)) \
                    or ""
                if name.split(".")[-1] == "hot_path":
                    hot.add(qn)
        if not hot:
            return
        # same-module reachability: self.m() -> Class.m, f() -> module f
        edges = {}
        for qn, cls, node in funcs:
            callees = set()
            for n in walk_host_stmts(node):
                cn = call_name(n)
                if not cn:
                    continue
                if cn.startswith("self.") and cls:
                    target = f"{cls}.{cn[5:]}"
                    if target in by_qual:
                        callees.add(target)
                elif cn in by_qual:
                    callees.add(cn)
            edges[qn] = callees
        reach, frontier = set(hot), list(hot)
        while frontier:
            for nxt in edges.get(frontier.pop(), ()):
                if nxt not in reach:
                    reach.add(nxt)
                    frontier.append(nxt)

        for qn in sorted(reach):
            for node in walk_host_stmts(by_qual[qn]):
                if not isinstance(node, ast.Call):
                    continue
                cn = call_name(node)
                msg = None
                if isinstance(node.func, ast.Attribute) \
                        and node.func.attr in _SYNC_ATTRS:
                    msg = f".{node.func.attr}() forces a device sync"
                elif cn in _SYNC_CALLS:
                    msg = f"{cn}() forces a device sync"
                elif cn in ("float", "bool") and node.args and not \
                        isinstance(node.args[0], ast.Constant):
                    msg = f"{cn}() on a computed value forces a device " \
                          "sync if it is a jax array"
                if msg:
                    yield sf.finding(
                        self.id, node,
                        f"{msg} inside hot path `{qn}` — hoist it off "
                        "the step loop, batch it with other reads, or "
                        "suppress naming the designed sync point")


# -- jit-cache-capture --------------------------------------------------------
_LRU_NAMES = {"functools.lru_cache", "lru_cache", "functools.cache",
              "cache"}


@register
class JitCacheCaptureChecker(Checker):
    id = "jit-cache-capture"
    doc = ("Module-level program caches must key on immutable config, "
           "never on live objects: an engine/module key (or an id() of "
           "one) pins multi-GB parameter dicts forever — or worse, "
           "id() recycling hands a NEW object another object's "
           "compiled program. The _STEP_CACHE/_ModelCfg rule from the "
           "serve engine, generalized. functools.lru_cache on methods "
           "is the same bug: self becomes a cache key and the instance "
           "becomes immortal.")

    def check(self, sf, ctx):
        module_dicts = set()
        for node in sf.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Dict):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        module_dicts.add(t.id)

        # (a) lru_cache on a method: self is hashed into every key
        for node in ast.walk(sf.tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            args = node.args.posonlyargs + node.args.args
            if not (args and args[0].arg in ("self", "cls")):
                continue
            for dec in node.decorator_list:
                name = dotted(dec) or dotted(getattr(dec, "func",
                                                     None)) or ""
                if name in _LRU_NAMES:
                    yield sf.finding(
                        self.id, dec,
                        f"lru_cache on method {node.name!r}: self "
                        "becomes part of every cache key, pinning "
                        "the instance (and any device buffers it "
                        "holds) for the cache's lifetime — cache "
                        "on a module-level function keyed by "
                        "immutable config")

        # (b)/(c) need receiver scope: id()-keyed LOCAL dicts are the
        # standard ephemeral graph-traversal idiom (ids stable while
        # the traversal holds the objects) and self-owned dicts keyed
        # by ids of objects the same instance owns are fine too.  The
        # bug class needs the cache to OUTLIVE the keyed object:
        # module-level dicts and caches passed in as parameters.
        for qn, cls, fn in functions_of(sf.tree):
            local_dicts, params, tainted = set(), set(), set()
            a = fn.args
            for arg in (a.posonlyargs + a.args + a.kwonlyargs
                        + ([a.vararg] if a.vararg else [])
                        + ([a.kwarg] if a.kwarg else [])):
                params.add(arg.arg)
            for n in walk_host_stmts(fn):
                if isinstance(n, ast.Assign):
                    if isinstance(n.value, (ast.Dict, ast.DictComp)):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                local_dicts.add(t.id)
                    # one-step taint: `key = (self, bucket)` — a BARE
                    # self (not self.attr / self.method()) in a local
                    # later used as a cache key is still a capture
                    elif _has_bare_self(n.value):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                tainted.add(t.id)

            def self_keyed(slc):
                return _has_bare_self(slc) or (
                    isinstance(slc, ast.Name) and slc.id in tainted)

            def shared(recv):
                """Receiver outlives the function: a module-level dict
                or a caller-owned cache parameter (minus self/cls)."""
                if not isinstance(recv, ast.Name):
                    return False
                if recv.id in local_dicts:
                    return False
                return recv.id in module_dicts or recv.id in params

            for n in walk_host_stmts(fn):
                if isinstance(n, ast.Assign):
                    for t in n.targets:
                        if not isinstance(t, ast.Subscript):
                            continue
                        if shared(t.value) and contains(
                                t.slice, self._is_id_call):
                            yield sf.finding(
                                self.id, t,
                                "cache key built from id(obj): ids are "
                                "recycled after GC (a fresh object can "
                                "inherit a dead object's compiled "
                                "program) and the entry pins whatever "
                                "the closure captured — key on the "
                                "object itself or on immutable config, "
                                "with bounded eviction")
                        elif isinstance(t.value, ast.Name) \
                                and t.value.id in module_dicts \
                                and self_keyed(t.slice):
                            yield sf.finding(
                                self.id, t,
                                f"module-level cache {t.value.id!r} "
                                "keyed by self: the cache outlives the "
                                "instance and retains it (and its "
                                "device buffers) forever — key on an "
                                "immutable config tuple (the "
                                "_STEP_CACHE/_ModelCfg rule)")
                elif isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and n.func.attr in ("get", "setdefault") \
                        and shared(n.func.value) \
                        and any(contains(arg, self._is_id_call)
                                for arg in n.args):
                    yield sf.finding(
                        self.id, n,
                        "cache lookup keyed by id(obj) — see the "
                        "paired store; key on the object or immutable "
                        "config")

    @staticmethod
    def _is_id_call(n):
        return isinstance(n, ast.Call) and isinstance(n.func, ast.Name) \
            and n.func.id == "id" and len(n.args) == 1


def _has_bare_self(node):
    """A Name 'self' used as a VALUE (not as the base of self.attr /
    self.method() — attribute access consumes it)."""
    if isinstance(node, ast.Name):
        return node.id == "self"
    if isinstance(node, ast.Attribute):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            return False
        return _has_bare_self(node.value)
    return any(_has_bare_self(c) for c in ast.iter_child_nodes(node))


# -- use-after-donate ---------------------------------------------------------
@register
class UseAfterDonateChecker(Checker):
    id = "use-after-donate"
    doc = ("donate_argnums hands the argument's buffer to XLA: after "
           "the call the array is logically deleted. CPU ignores "
           "donation, so a read-after-donate passes every CPU test and "
           "fails only on TPU (with a deleted-buffer error at best, "
           "silent corruption via aliasing at worst). The checker "
           "tracks jits created with donate_argnums — including "
           "through the repo's _donate() TPU-only guard — and flags "
           "reads of a donated name/attribute after the donating call "
           "in the same function, unless it was reassigned (the "
           "`x, … = f(x, …)` commit idiom).")

    def check(self, sf, ctx):
        funcs = functions_of(sf.tree)

        def annotated(n):
            """`# mxtpu-lint: donates=i,j` positions on any line of the
            assignment — the opt-in for factory-returned donating
            programs (e.g. cached_sgd_step) that per-module analysis
            cannot see into."""
            for ln in range(n.lineno, getattr(n, "end_lineno",
                                              n.lineno) + 1):
                if ln in sf.donates:
                    return list(sf.donates[ln])
            return None

        donated_fns = {}        # callable dotted-name -> positions
        for _, cls, node in funcs:
            for n in walk_host_stmts(node):
                if not isinstance(n, ast.Assign):
                    continue
                pos = donated_argnums(n.value) if isinstance(
                    n.value, ast.Call) else None
                pos = pos or annotated(n)
                if not pos:
                    continue
                for t in n.targets:
                    name = dotted(t)
                    if name:
                        donated_fns[name] = pos
        # module-level jits too
        for n in sf.tree.body:
            if isinstance(n, ast.Assign):
                pos = donated_argnums(n.value) if isinstance(
                    n.value, ast.Call) else None
                pos = pos or annotated(n)
                if pos:
                    for t in n.targets:
                        name = dotted(t)
                        if name:
                            donated_fns[name] = pos
        if not donated_fns:
            return

        for qn, cls, fn_node in funcs:
            yield from self._check_fn(sf, qn, fn_node, donated_fns)

    def _check_fn(self, sf, qn, fn_node, donated_fns):
        # statement-level path bookkeeping: for each donating call,
        # "later" means the statements AFTER its enclosing statement in
        # every enclosing block (linear flow only — no sibling
        # branches, no loop back-edges: branch- and loop-carried flows
        # are out of scope, trading false negatives for zero noise).
        donations = []        # (chain, donating stmt, path)

        def scan(block, path):
            for i, stmt in enumerate(block):
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                here = path + [(block, i)]
                for node in _stmt_nodes(stmt):
                    if isinstance(node, ast.Call):
                        cn = call_name(node)
                        pos = donated_fns.get(cn) if cn else None
                        if not pos:
                            continue
                        for p in pos:
                            if p < len(node.args):
                                chain = dotted(node.args[p])
                                if chain:
                                    donations.append(
                                        (chain, stmt, node, list(here)))
                for sub in _sub_blocks(stmt):
                    scan(sub, here)

        scan(fn_node.body, [])

        for chain, stmt, call, path in donations:
            # reassigned by the donating statement itself (the
            # `x, … = f(x, …)` commit idiom) — satisfied immediately
            if chain in _stmt_store_chains(stmt):
                continue
            # linearized execution order after the donating statement:
            # rest of the innermost block first, then outer blocks
            later = []
            for block, i in reversed(path):
                later.extend(block[i + 1:])
            reassigned = False
            for nxt in later:
                if reassigned:
                    break
                loads, stores = _stmt_chain_uses(nxt)
                if chain in loads:
                    yield sf.finding(
                        self.id, call,
                        f"`{chain}` is read at line "
                        f"{loads[chain]} after being donated here "
                        "(donate_argnums): on TPU its buffer is gone "
                        "after this call — reassign it from the "
                        "program's outputs or drop the donation")
                    break
                if chain in stores:
                    reassigned = True


def _sub_blocks(stmt):
    """Nested statement blocks of a compound statement (if/for/while/
    with/try bodies), excluding function/class defs."""
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if isinstance(block, list) and not isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                       ast.ClassDef)):
            yield block
    for h in getattr(stmt, "handlers", []) or []:
        yield h.body


def _stmt_nodes(stmt):
    """Nodes belonging to the statement HEAD only (test/items/value —
    not nested blocks, not nested defs)."""
    blocks = set()
    for b in _sub_blocks(stmt):
        blocks.update(id(s) for s in b)

    def walk(node):
        yield node
        for child in ast.iter_child_nodes(node):
            if id(child) in blocks or isinstance(
                    child, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
                continue
            yield from walk(child)

    yield from walk(stmt)


def _stmt_chain_uses(stmt):
    """({chain: first load line}, {chain: first store line}) over a
    whole statement including nested blocks (but not nested defs)."""
    loads, stores = {}, {}
    for node in _walk_skip_defs(stmt):
        if isinstance(node, (ast.Name, ast.Attribute)):
            chain = dotted(node)
            if not chain:
                continue
            book = stores if isinstance(node.ctx,
                                        (ast.Store, ast.Del)) else loads
            book.setdefault(chain, node.lineno)
    return loads, stores


def _stmt_store_chains(stmt):
    """Chains stored by the statement head (assignment targets)."""
    out = set()
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    for t in targets:
        for node in ast.walk(t):
            if isinstance(node, (ast.Name, ast.Attribute)) \
                    and isinstance(node.ctx, ast.Store):
                chain = dotted(node)
                if chain:
                    out.add(chain)
    return out


# -- env-discipline -----------------------------------------------------------
_ENV_PARSERS = {"env_flag", "env_int", "env_float", "base.env_flag",
                "base.env_int", "base.env_float"}


@register
class EnvDisciplineChecker(Checker):
    id = "env-discipline"
    doc = ("MXTPU_* knobs are the runtime-config contract: every name "
           "must have a row in docs/env_vars.md (the drift gate "
           "tools/check_env_docs.py pioneered, folded into this "
           "checker), and boolean/numeric knobs must parse through "
           "base.env_flag/env_int/env_float so accepted spellings "
           "can't fork per call site (inline int(os.environ[...]) "
           "crashes on a malformed value; ad-hoc truthiness helpers "
           "drift).")

    def check(self, sf, ctx):
        docs = ctx.doc_vars()
        var_re = ctx.ENV_VAR_RE
        # (u) undocumented vars: text-level, any mention counts (same
        # contract as the original check_env_docs gate)
        for i, line in enumerate(sf.lines, 1):
            for var in var_re.findall(line):
                if var not in docs:
                    f = sf.finding(self.id, _FakeNode(i),
                                   f"{var} is not documented in "
                                   f"docs/env_vars.md — add a row "
                                   "(name, default, meaning)")
                    yield f
        # (p) inline parsing of MXTPU_* reads
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Name) and fn.id in ("int", "float",
                                                      "bool"):
                # contains(), not a direct match: `int(get(...) or 1)`
                # style wrappers are the same inline parse
                if node.args and contains(node.args[0],
                                          self._mxtpu_env_read):
                    yield sf.finding(
                        self.id, node,
                        f"inline {fn.id}() over an MXTPU_* env read — "
                        "use base.env_flag/env_int/env_float (one "
                        "parser, malformed values fall back instead "
                        "of raising)")
            elif isinstance(fn, ast.Name) \
                    and fn.id.lstrip("_").startswith("env") \
                    and fn.id not in _ENV_PARSERS \
                    and any(self._mentions_mxtpu(a, ctx)
                            for a in node.args):
                yield sf.finding(
                    self.id, node,
                    f"custom env parser {fn.id}() over an MXTPU_* "
                    "knob — accepted spellings fork per helper; use "
                    "base.env_flag/env_int/env_float")

    @staticmethod
    def _mxtpu_env_read(node):
        """os.environ.get("MXTPU_…"), os.getenv("MXTPU_…"),
        os.environ["MXTPU_…"]."""
        def lit_mxtpu(n):
            return isinstance(n, ast.Constant) \
                and isinstance(n.value, str) \
                and n.value.startswith("MXTPU_")

        if isinstance(node, ast.Call):
            cn = call_name(node) or ""
            if cn in ("os.environ.get", "os.getenv", "environ.get",
                      "getenv") and node.args:
                return lit_mxtpu(node.args[0])
        if isinstance(node, ast.Subscript):
            base = dotted(node.value)
            if base in ("os.environ", "environ"):
                return lit_mxtpu(node.slice)
        return False

    def _mentions_mxtpu(self, node, ctx):
        """An env read of an MXTPU var, or an MXTPU_* name literal —
        `_env("MXTPU_SERVE_TP", 1)`-style helpers take the NAME, not
        the read, and must not evade the rule."""
        def mxtpu_literal(n):
            return isinstance(n, ast.Constant) \
                and isinstance(n.value, str) \
                and n.value.startswith("MXTPU_")

        return contains(node, self._mxtpu_env_read) \
            or contains(node, mxtpu_literal)


class _FakeNode:
    """Line-only anchor for text-level findings."""

    def __init__(self, lineno, col_offset=0):
        self.lineno = lineno
        self.col_offset = col_offset


# -- unlocked-shared-state ----------------------------------------------------
_MUTATORS = {"append", "appendleft", "extend", "insert", "pop", "popleft",
             "popitem", "remove", "clear", "update", "add", "discard",
             "setdefault", "sort", "reverse"}


@register
class UnlockedSharedStateChecker(Checker):
    id = "unlocked-shared-state"
    doc = ("An attribute annotated `# guarded-by: <lock>` on its "
           "declaring assignment documents a locking contract; this "
           "checker enforces it lexically: every mutation (assignment, "
           "augmented assignment, item store, or a mutating method "
           "like .append/.pop/.update) in any method other than "
           "__init__ must sit inside `with self.<lock>:`. Cross-thread "
           "state in the serve scheduler, block manager, flight "
           "recorder and prefetch iterators carries these "
           "annotations.")

    def check(self, sf, ctx):
        for node in sf.tree.body:
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(sf, node)

    def _check_class(self, sf, cls):
        guarded = {}          # attr -> lock attr name (self.<lock>)
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        for m in methods:
            for node in ast.walk(m):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                lock = sf.guards.get(node.lineno)
                if not lock:
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    chain = dotted(t)
                    if chain and chain.startswith("self."):
                        guarded[chain[5:]] = lock.split(".")[-1]
        if not guarded:
            return
        for m in methods:
            if m.name == "__init__":
                continue      # construction precedes sharing
            yield from self._check_method(sf, cls.name, m, guarded)

    def _check_method(self, sf, clsname, method, guarded):
        def visit(node, locks):
            if isinstance(node, ast.With):
                held = set(locks)
                for item in node.items:
                    chain = dotted(item.context_expr)
                    if chain and chain.startswith("self."):
                        held.add(chain[5:])
                for child in node.body:
                    yield from visit(child, held)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                return
            yield from self._mutations(sf, clsname, method, node,
                                       locks, guarded)
            for child in ast.iter_child_nodes(node):
                yield from visit(child, locks)

        for stmt in method.body:
            yield from visit(stmt, set())

    def _mutations(self, sf, clsname, method, node, locks, guarded):
        def flag(attr, what, anchor):
            lock = guarded[attr]
            if lock not in locks:
                yield sf.finding(
                    self.id, anchor,
                    f"{what} of self.{attr} in "
                    f"{clsname}.{method.name} outside `with "
                    f"self.{lock}` (declared # guarded-by: {lock})")

        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                chain = dotted(t)
                if chain and chain.startswith("self.") \
                        and chain[5:] in guarded:
                    yield from flag(chain[5:], "assignment", node)
                elif isinstance(t, ast.Subscript):
                    chain = dotted(t.value)
                    if chain and chain.startswith("self.") \
                            and chain[5:] in guarded:
                        yield from flag(chain[5:], "item store", node)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                chain = dotted(base)
                if chain and chain.startswith("self.") \
                        and chain[5:] in guarded:
                    yield from flag(chain[5:], "delete", node)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in _MUTATORS:
            chain = dotted(node.func.value)
            if chain and chain.startswith("self.") \
                    and chain[5:] in guarded:
                yield from flag(chain[5:],
                                f".{node.func.attr}()", node)


# -- swallowed-exception ------------------------------------------------------
@register
class SwallowedExceptionChecker(Checker):
    id = "swallowed-exception"
    doc = ("A bare `except:` or `except Exception:` whose body is only "
           "pass/continue erases the failure: no counter moves, no log "
           "line lands, and the outage is debugged from nothing. "
           "Handlers must at minimum count an errors-total metric or "
           "log before continuing; intentional last-resort guards "
           "(interpreter-exit paths) carry suppressions. Handlers that "
           "assign a fallback, return, raise, or call anything are "
           "considered handled.")

    _BROAD = {"Exception", "BaseException"}

    def check(self, sf, ctx):
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node.type):
                continue
            if self._body_swallows(node.body):
                what = "bare except:" if node.type is None else \
                    f"except {ast.unparse(node.type)}:"
                yield sf.finding(
                    self.id, node,
                    f"{what} with a pass/continue-only body swallows "
                    "the failure — count an mxtpu_*_errors_total "
                    "counter or log before continuing (or narrow the "
                    "exception type to the expected case)")

    def _is_broad(self, type_node):
        if type_node is None:
            return True
        if isinstance(type_node, ast.Tuple):
            return any(self._is_broad(e) for e in type_node.elts)
        name = dotted(type_node)
        return name in self._BROAD if name else False

    @staticmethod
    def _body_swallows(body):
        for stmt in body:
            if isinstance(stmt, (ast.Pass, ast.Continue)):
                continue
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                         ast.Constant):
                continue      # docstring/comment-like constant
            return False      # anything else is handling
        return True
