"""mxtpu-lint core: source scanning, checker registry, suppressions,
baseline bookkeeping.

The linter is pure stdlib (``ast`` + ``tokenize``) so it can run in any
environment the package installs into — including the tier-1 test tier
with ``JAX_PLATFORMS=cpu`` — without importing jax or the modules under
analysis.  Everything is text-level: checkers receive a parsed
:class:`SourceFile` and return :class:`Finding` objects.

Vocabulary:

* **checker** — one registered rule (``wall-clock``, ``host-sync``, …)
  with a stable id; see checkers.py for the implementations.
* **suppression** — ``# mxtpu-lint: disable=<id>[,<id>…] (reason)``
  on the offending line (or on a comment-only line directly above it).
  ``disable=all`` silences every checker for that line.  The reason
  parenthetical is convention, not syntax — but reviews should treat a
  reasonless waiver as a smell.
* **baseline** — a committed JSON file of grandfathered findings; the
  CLI fails only on findings NOT in the baseline, so the gate can land
  before the burn-down finishes.  Entries match on
  ``(check, path, stripped source line)`` — stable across unrelated
  line drift — with a count, so N identical offending lines in one
  file need a count of N.
"""

from __future__ import annotations

import ast
import io
import json
import os
import re
import tokenize

__all__ = ["Finding", "SourceFile", "LintContext", "register",
           "all_checkers", "run_lint", "load_baseline", "save_baseline",
           "apply_baseline", "iter_py_files"]

SUPPRESS_RE = re.compile(
    r"#\s*mxtpu-lint:\s*disable=([A-Za-z0-9_,\- ]+)")
GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
# `x = make_step(...)   # mxtpu-lint: donates=0,3` — declares that the
# bound callable donates those positional args (how factory-returned
# donating programs, invisible to cross-module analysis, opt into the
# use-after-donate checker at their call sites)
DONATES_RE = re.compile(r"#\s*mxtpu-lint:\s*donates=([0-9, ]+)")


class Finding:
    """One lint finding, pinned to a source line."""

    __slots__ = ("check", "path", "line", "col", "message", "code")

    def __init__(self, check, path, line, col, message, code=""):
        self.check = check
        self.path = path
        self.line = int(line)
        self.col = int(col)
        self.message = message
        self.code = code.strip()

    def baseline_key(self):
        """(check, path, stripped code line) — survives line drift."""
        return (self.check, self.path, self.code)

    def to_dict(self):
        return {"check": self.check, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "code": self.code}

    def render(self):
        return f"{self.path}:{self.line}:{self.col}: " \
               f"[{self.check}] {self.message}"

    def __repr__(self):
        return f"Finding({self.render()!r})"


class SourceFile:
    """One parsed source file: AST + per-line comment annotations.

    ``suppressions`` maps line -> set of checker ids disabled there
    (``{"all"}`` disables everything).  A suppression on a comment-only
    line applies to the next line, so multi-line statements can carry
    their waiver above the code.  ``guards`` maps line -> lock name
    from ``# guarded-by: <lock>`` annotations.
    """

    def __init__(self, path, text, relpath=None):
        self.path = path
        self.relpath = relpath or path
        self.text = text
        self.lines = text.splitlines()
        self.tree = ast.parse(text)       # SyntaxError propagates
        self.suppressions = {}
        self.guards = {}
        self.donates = {}          # line -> (donated positions, ...)
        self._scan_comments()

    def _scan_comments(self):
        comments = {}                      # line -> comment text
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError):
            # fall back to a naive per-line scan; a '#' inside a string
            # may over-match, which can only over-suppress one line
            for i, line in enumerate(self.lines, 1):
                if "#" in line:
                    comments[i] = line[line.index("#"):]
        def code_before_hash(i):
            line = self.lines[i - 1] if 0 < i <= len(self.lines) else ""
            return line[:line.index("#")] if "#" in line else line

        for lineno, comment in comments.items():
            target = lineno
            if not code_before_hash(lineno).strip():
                # standalone comment: applies to the next code line,
                # skipping over the rest of the comment block
                target = lineno + 1
                while target <= len(self.lines) and (
                        not self.lines[target - 1].strip()
                        or self.lines[target - 1].lstrip()
                        .startswith("#")):
                    target += 1
            m = SUPPRESS_RE.search(comment)
            if m:
                checks = {c.strip() for c in m.group(1).split(",")
                          if c.strip()}
                self.suppressions.setdefault(target, set()).update(checks)
            g = GUARD_RE.search(comment)
            if g:
                # guard annotations always bind to the code on THEIR
                # line (they sit on the attribute assignment)
                self.guards[lineno] = g.group(1)
            d = DONATES_RE.search(comment)
            if d:
                pos = tuple(int(x) for x in d.group(1).split(",")
                            if x.strip())
                if pos:
                    self.donates[target] = pos

    def suppressed(self, line, check):
        s = self.suppressions.get(line)
        return bool(s) and (check in s or "all" in s)

    def finding(self, check, node, message):
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        code = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        return Finding(check, self.relpath, line, col, message, code)


class LintContext:
    """Run-wide state checkers may consult (repo root, documented env
    vars).  Built once per ``run_lint`` call."""

    ENV_DOC = os.path.join("docs", "env_vars.md")
    ENV_VAR_RE = re.compile(r"\bMXTPU_[A-Z0-9]+(?:_[A-Z0-9]+)*\b")

    def __init__(self, repo):
        self.repo = repo
        self._doc_vars = None

    def doc_vars(self):
        """MXTPU_* names documented in docs/env_vars.md (empty set when
        the doc is absent — every var is then a finding, which is the
        correct failure mode for a repo that lost its env table)."""
        if self._doc_vars is None:
            path = os.path.join(self.repo, self.ENV_DOC)
            try:
                with open(path, encoding="utf-8") as f:
                    self._doc_vars = set(self.ENV_VAR_RE.findall(f.read()))
            except OSError:
                self._doc_vars = set()
        return self._doc_vars


# -- checker registry ---------------------------------------------------------
_CHECKERS = {}


def register(cls):
    """Class decorator: add a checker to the registry by its ``id``."""
    if not getattr(cls, "id", None):
        raise ValueError(f"checker {cls!r} needs a non-empty id")
    if cls.id in _CHECKERS:
        raise ValueError(f"duplicate checker id {cls.id!r}")
    _CHECKERS[cls.id] = cls
    return cls


def all_checkers():
    """{id: checker class}, import-complete (checkers.py registers on
    import)."""
    from . import checkers  # noqa: F401  (registration side effect)

    return dict(_CHECKERS)


# -- running ------------------------------------------------------------------
def iter_py_files(paths):
    """Yield every .py file under the given files/directories, skipping
    __pycache__ and hidden directories, in sorted order."""
    seen = set()
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and p not in seen:
                seen.add(p)
                yield p
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = sorted(d for d in dirnames
                                 if d != "__pycache__"
                                 and not d.startswith("."))
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    full = os.path.join(dirpath, fn)
                    if full not in seen:
                        seen.add(full)
                        yield full


def run_lint(paths, repo=None, checks=None):
    """Lint every .py file under ``paths``.

    Returns ``(findings, errors)`` — findings sorted by (path, line,
    check) with suppressed ones already dropped; errors is a list of
    ``(path, message)`` for files that failed to parse (a parse failure
    is loud, not silent: the CLI reports and fails on them).
    """
    repo = repo or os.getcwd()
    ctx = LintContext(repo)
    registry = all_checkers()
    if checks:
        unknown = set(checks) - set(registry)
        if unknown:
            raise ValueError(f"unknown checker(s): {sorted(unknown)}; "
                             f"known: {sorted(registry)}")
        registry = {k: v for k, v in registry.items() if k in checks}
    instances = [cls() for _, cls in sorted(registry.items())]

    findings, errors = [], []
    for path in iter_py_files(paths):
        rel = os.path.relpath(path, repo)
        if rel.startswith(".."):
            rel = path
        try:
            with open(path, encoding="utf-8", errors="replace") as f:
                text = f.read()
            sf = SourceFile(path, text, relpath=rel.replace(os.sep, "/"))
        except SyntaxError as e:
            errors.append((rel, f"syntax error: {e}"))
            continue
        except OSError as e:
            errors.append((rel, f"unreadable: {e}"))
            continue
        for chk in instances:
            for finding in chk.check(sf, ctx):
                if not sf.suppressed(finding.line, finding.check):
                    findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.check))
    return findings, errors


# -- baseline -----------------------------------------------------------------
def load_baseline(path):
    """Baseline file -> multiset {(check, path, code): count}.  Every
    entry is expected to carry a ``why`` justifying its grandfathering;
    absent files mean an empty baseline."""
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except OSError:
        return {}
    counts = {}
    for e in data.get("entries", []):
        key = (e["check"], e["path"], e.get("code", "").strip())
        counts[key] = counts.get(key, 0) + int(e.get("count", 1))
    return counts


def save_baseline(path, findings, why="grandfathered at baseline creation"):
    """Write the current findings as a baseline (the burn-down
    starting point).  Identical (check, path, code) findings fold into
    one entry with a count."""
    counts = {}
    for f in findings:
        counts[f.baseline_key()] = counts.get(f.baseline_key(), 0) + 1
    entries = [{"check": c, "path": p, "code": code, "count": n,
                "why": why}
               for (c, p, code), n in sorted(counts.items())]
    payload = {
        "comment": "mxtpu-lint baseline: grandfathered findings. Every "
                   "entry needs a 'why'; new code must be clean. Shrink "
                   "this file, never grow it.",
        "entries": entries}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def apply_baseline(findings, baseline):
    """Split findings into (new, baselined) against the baseline
    multiset, and report stale baseline entries (entries no current
    finding matched — they should be deleted).

    Returns ``(new, baselined, stale)``.
    """
    remaining = dict(baseline)
    new, matched = [], []
    for f in findings:
        key = f.baseline_key()
        if remaining.get(key, 0) > 0:
            remaining[key] -= 1
            matched.append(f)
        else:
            new.append(f)
    stale = [k for k, n in remaining.items() if n > 0]
    return new, matched, stale
