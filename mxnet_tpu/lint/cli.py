"""mxtpu-lint command line: human and JSON reports, baseline workflow.

Exit codes: 0 clean (all findings baselined or none), 1 new findings
or parse errors, 2 usage errors.  ``--json`` emits one machine-readable
document (the bench_watch ``lint`` stage consumes it to trend finding
counts per checker).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .core import (all_checkers, apply_baseline, load_baseline, run_lint,
                   save_baseline)

DEFAULT_BASELINE = os.path.join("tools", "lint_baseline.json")


def counts_by_check(findings):
    out = {}
    for f in findings:
        out[f.check] = out.get(f.check, 0) + 1
    return out


def build_parser():
    p = argparse.ArgumentParser(
        prog="mxtpu_lint",
        description="JAX-aware static analysis for mxnet_tpu "
                    "(see docs/how_to/static_analysis.md)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint "
                        "(default: mxnet_tpu tools, relative to --repo)")
    p.add_argument("--repo", default=None,
                   help="repo root (default: parent of this tool)")
    p.add_argument("--checks", default=None,
                   help="comma-separated checker ids to run "
                        "(default: all)")
    p.add_argument("--baseline", default=None,
                   help=f"baseline JSON path (default: "
                        f"{DEFAULT_BASELINE} under --repo when it "
                        "exists; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current findings to --baseline and exit "
                        "0 (the burn-down starting point)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit a JSON report instead of text")
    p.add_argument("--list-checks", action="store_true",
                   help="list checker ids with their rationale")
    return p


def main(argv=None):
    args = build_parser().parse_args(argv)
    if args.list_checks:
        for cid, cls in sorted(all_checkers().items()):
            doc = " ".join((cls.doc or "").split())
            print(f"{cid}\n    {doc}\n")
        return 0

    repo = args.repo or os.getcwd()
    paths = args.paths or [os.path.join(repo, "mxnet_tpu"),
                           os.path.join(repo, "tools")]
    checks = [c.strip() for c in args.checks.split(",")] \
        if args.checks else None
    try:
        findings, errors = run_lint(paths, repo=repo, checks=checks)
    except ValueError as e:
        print(f"mxtpu-lint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None:
        cand = os.path.join(repo, DEFAULT_BASELINE)
        baseline_path = cand if os.path.exists(cand) else "none"
    elif baseline_path != "none" and not os.path.isabs(baseline_path):
        baseline_path = os.path.join(repo, baseline_path)

    if args.write_baseline:
        if baseline_path == "none":
            baseline_path = os.path.join(repo, DEFAULT_BASELINE)
        save_baseline(baseline_path, findings)
        print(f"baseline written: {baseline_path} "
              f"({len(findings)} finding(s))")
        return 0

    baseline = load_baseline(baseline_path) \
        if baseline_path != "none" else {}
    new, baselined, stale = apply_baseline(findings, baseline)

    if args.as_json:
        doc = {"findings": [f.to_dict() for f in new],
               "baselined": len(baselined),
               "stale_baseline_entries": [list(k) for k in stale],
               "errors": [{"path": p, "message": m} for p, m in errors],
               "counts": counts_by_check(new),
               "counts_all": counts_by_check(findings),
               "checks": sorted(all_checkers() if not checks
                                else checks),
               "clean": not new and not errors}
        print(json.dumps(doc, indent=1, sort_keys=True))
        return 0 if doc["clean"] else 1

    for path, msg in errors:
        print(f"{path}: ERROR {msg}", file=sys.stderr)
    for f in new:
        print(f.render())
        if f.code:
            print(f"    {f.code}")
    if stale:
        print(f"note: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} matched nothing — "
              "delete them:", file=sys.stderr)
        for check, path, code in stale:
            print(f"    [{check}] {path}: {code}", file=sys.stderr)
    if new or errors:
        by = counts_by_check(new)
        summary = ", ".join(f"{k}={v}" for k, v in sorted(by.items()))
        print(f"mxtpu-lint: {len(new)} new finding(s) "
              f"({summary or 'parse errors only'}), "
              f"{len(baselined)} baselined, {len(errors)} error(s)",
              file=sys.stderr)
        return 1
    if baselined:
        print(f"mxtpu-lint: clean — 0 new findings, "
              f"{len(baselined)} baselined")
    else:
        print("mxtpu-lint: clean — 0 findings")
    return 0
