"""Zero-dependency source annotations the linter understands.

This module must stay import-cycle-free: anything in the package (the
serve engine, the fused train step, io prefetch) may import it to mark
hot entry points, so it imports nothing from mxnet_tpu and nothing
heavyweight.

Two annotation surfaces exist:

``@hot_path``
    Marks a function as an entry point of a latency-critical loop (a
    serve step, a fused train step).  The ``host-sync`` checker seeds
    its reachability walk at these functions: any ``float()`` /
    ``bool()`` / ``.item()`` / ``np.asarray`` style forced device→host
    sync inside them (or inside same-module functions they call) is a
    finding unless suppressed with a reason.

``# guarded-by: <lock>`` (comment, not code)
    On a ``self.attr = ...`` line (usually in ``__init__``), documents
    that ``attr`` must only be mutated while holding ``self.<lock>``.
    The ``unlocked-shared-state`` checker enforces it lexically.

Suppressions are comments too::

    x = time.time()   # mxtpu-lint: disable=wall-clock (jsonl timestamp)

A comment-only line suppresses the next code line, so long statements
can carry their waiver above them.
"""

__all__ = ["hot_path", "HOT_PATH_ATTR"]

HOT_PATH_ATTR = "__mxtpu_hot_path__"


def hot_path(fn):
    """Mark ``fn`` as a hot entry point for the ``host-sync`` checker.

    Runtime-inert: the only effect is a marker attribute (and the
    decorator's *name* appearing in the AST, which is what the static
    checker actually keys on)."""
    try:
        setattr(fn, HOT_PATH_ATTR, True)
    except (AttributeError, TypeError):
        pass          # builtins/partials: the AST marker still works
    return fn
