"""mxtpu-lint: JAX-aware static analysis for the TPU framework.

An AST-based checker suite encoding the contracts this codebase keeps
re-learning the hard way: no wall-clock in perf paths, no forced
device syncs in step loops, no live objects in program-cache keys, no
reads of donated buffers, one parser for MXTPU_* env knobs, documented
lock discipline, and no silently swallowed exceptions.

Entry points:

* ``python tools/mxtpu_lint.py mxnet_tpu tools`` — the CLI (human or
  ``--json`` reports, baseline management).
* ``tests/test_lint.py`` — the tier-1 gate: the tree must be clean
  against the committed baseline on every test run.
* :func:`mxnet_tpu.lint.run_lint` — programmatic API.
* :func:`mxnet_tpu.lint.hot_path` — decorator marking hot entry points
  for the ``host-sync`` checker (runtime-inert).

See docs/how_to/static_analysis.md for the checker gallery, the
suppression / baseline workflow, and how to add a checker.
"""

from .annotations import hot_path
from .core import (Finding, LintContext, SourceFile, all_checkers,
                   apply_baseline, iter_py_files, load_baseline,
                   run_lint, save_baseline)

__all__ = ["hot_path", "Finding", "SourceFile", "LintContext",
           "all_checkers", "run_lint", "iter_py_files",
           "load_baseline", "save_baseline", "apply_baseline"]
