"""Legacy helpers (reference python/mxnet/misc.py).

The reference's ``misc.LearningRateScheduler`` predates
``lr_scheduler.LRScheduler``; it survives there as a deprecated alias
and does here too — new code should use ``mx.lr_scheduler``.
"""

from __future__ import annotations

__all__ = ["LearningRateScheduler", "FactorScheduler"]


class LearningRateScheduler:
    """Base class of the legacy scheduler API (reference misc.py:7-34):
    a callable ``iteration -> learning rate`` carrying ``base_lr``."""

    def __init__(self):
        self.base_lr = 0.01

    def __call__(self, iteration):
        raise NotImplementedError("must override this")


class FactorScheduler(LearningRateScheduler):
    """lr = base_lr * factor^(iteration // step) (reference
    misc.py FactorScheduler)."""

    def __init__(self, step, factor=0.1):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor >= 1.0:
            raise ValueError("Factor must be less than 1 to make lr reduce")
        self.step = step
        self.factor = factor

    def __call__(self, iteration):
        return self.base_lr * (self.factor ** int(iteration / self.step))
