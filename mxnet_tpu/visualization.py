"""Network visualization (rebuild of python/mxnet/visualization.py):
``print_summary`` (layer table with params/flops-ish info) and
``plot_network`` (graphviz dot; returns the Digraph if graphviz is
installed, else the dot source string)."""

from __future__ import annotations

import json

from .base import MXNetError

__all__ = ["print_summary", "plot_network"]


def print_summary(symbol, shape=None, line_length=120, positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a per-layer summary table (reference visualization.py:25)."""
    show_shape = False
    shape_dict = {}
    if shape is not None:
        show_shape = True
        interals = symbol.get_internals()
        _, out_shapes, _ = interals.infer_shape(**shape)
        if out_shapes is None:
            raise MXNetError("Input shape is incomplete")
        shape_dict = dict(zip(interals.list_outputs(), out_shapes))
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    if positions[-1] <= 1:
        positions = [int(line_length * p) for p in positions]
    to_display = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    def print_row(fields, positions):
        line = ""
        for i, field in enumerate(fields):
            line += str(field)
            line = line[:positions[i]]
            line += " " * (positions[i] - len(line))
        print(line)

    print("_" * line_length)
    print_row(to_display, positions)
    print("=" * line_length)
    total_params = [0]

    def print_layer_summary(node, out_shape):
        op = node["op"]
        pre_node = []
        pre_filter = 0
        if op != "null":
            inputs = node["inputs"]
            for item in inputs:
                input_node = nodes[item[0]]
                input_name = input_node["name"]
                if input_node["op"] != "null" or item[0] in set(conf["arg_nodes"]):
                    if not input_name.startswith(node["name"]):
                        pre_node.append(input_name)
        cur_param = 0
        if show_shape and op != "null":
            key = node["name"] + "_output"
            if key in shape_dict:
                out_shape = shape_dict[key]
        for input_entry in node.get("inputs", []):
            input_node = nodes[input_entry[0]]
            if input_node["op"] == "null" and input_node["name"].startswith(
                    node["name"] + "_"):
                key = input_node["name"] + "_output"
                if key in shape_dict:
                    p = 1
                    for d in shape_dict[key]:
                        p *= d
                    cur_param += p
        first_connection = pre_node[0] if pre_node else ""
        fields = [f"{node['name']}({op})", str(out_shape) if out_shape else "",
                  cur_param, first_connection]
        print_row(fields, positions)
        for connection in pre_node[1:]:
            print_row(["", "", "", connection], positions)
        total_params[0] += cur_param

    for i, node in enumerate(nodes):
        if node["op"] == "null":
            continue
        out_shape = None
        print_layer_summary(node, out_shape)
        print(("=" if i == len(nodes) - 1 else "_") * line_length)
    print(f"Total params: {total_params[0]}")
    print("_" * line_length)


def plot_network(symbol, title="plot", shape=None, node_attrs=None):
    """Build a graphviz Digraph of the network (visualization.py:97).

    Falls back to returning the dot source string if graphviz is absent.
    """
    node_attrs = node_attrs or {}
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]
    heads = {h[0] for h in conf["heads"]}

    fill_map = {"FullyConnected": "#fb8072", "Convolution": "#fb8072",
                "Activation": "#ffffb3", "LeakyReLU": "#ffffb3",
                "BatchNorm": "#bebada", "Pooling": "#80b1d3",
                "Concat": "#fdb462", "Flatten": "#fdb462",
                "Reshape": "#fdb462", "SoftmaxOutput": "#b3de69"}

    lines = [f"digraph {json.dumps(title)} {{"]
    for i, node in enumerate(nodes):
        op = node["op"]
        name = node["name"]
        if op == "null" and i not in heads and not any(
                i == item[0] for n in nodes for item in n.get("inputs", [])):
            continue
        if op == "null":
            label = name
            color = "#8dd3c7"
        else:
            param = node.get("param", {})
            label = f"{op}\\n{name}"
            if op == "Convolution":
                label = f"Convolution\\n{param.get('kernel', '?')}/{param.get('stride', '1')},{param.get('num_filter', '?')}"
            elif op == "FullyConnected":
                label = f"FullyConnected\\n{param.get('num_hidden', '?')}"
            color = fill_map.get(op, "#fccde5")
        lines.append(
            f'  n{i} [label="{label}", style=filled, fillcolor="{color}", shape=box];')
    for i, node in enumerate(nodes):
        for item in node.get("inputs", []):
            src = nodes[item[0]]
            if src["op"] == "null" and not src["name"].endswith(
                    ("weight", "bias", "gamma", "beta", "moving_mean",
                     "moving_var", "label")):
                lines.append(f"  n{item[0]} -> n{i};")
            elif src["op"] != "null":
                lines.append(f"  n{item[0]} -> n{i};")
    lines.append("}")
    dot_source = "\n".join(lines)
    try:
        from graphviz import Source

        return Source(dot_source)
    except ImportError:
        return dot_source
