"""Post-training int8 quantization (beyond the 2016 reference; later
MXNet grew contrib/quantize.py — this is the TPU-native build of that
capability over ops/quantized.py).

``quantize_model`` rewrites a trained symbol so every eligible
FullyConnected / Convolution runs its quantized twin:

- always: per-output-channel symmetric int8 weights (+ f32 scale
  vector) — 4x smaller weight memory/bandwidth, activation-dtype math.
- with ``calib_data``: per-layer activation ranges are observed on
  real batches and baked in as ``act_scale``, so the contraction
  itself runs int8 x int8 -> int32 on the MXU (double int8 throughput
  on v5e+).

Usage::

    qsym, qargs, qaux = quantize_model(sym, arg_params, aux_params,
                                       calib_data=iter_or_batches)
    exe = qsym.simple_bind(mx.cpu(), grad_req="null", data=(N, ...))

Non-eligible layers (grouped/dilated convs) and names in ``exclude=``
pass through unchanged.  The first conv is a common exclusion (image
inputs have quantization-hostile statistics): ``exclude=('conv0',)``.
"""

from __future__ import annotations

import ast
import json

import numpy as np

from .. import ndarray as nd
from .. import symbol as sym_mod
from ..base import MXNetError
from ..context import cpu as cpu_ctx

__all__ = ["quantize_model", "quantize_weight"]

_QUANTIZABLE = {"FullyConnected": "QuantizedFullyConnected",
                "Convolution": "QuantizedConvolution"}
# params the quantized conv twin does not carry: XLA-internal knobs get
# dropped silently; structural options make the layer ineligible
_CONV_DROP = ("workspace", "cudnn_tune", "cudnn_off", "num_group",
              "dilate")


def quantize_weight(w):
    """Per-output-channel symmetric int8: returns (int8 array, f32
    scales) with w ≈ wq * scale[:, None, ...]."""
    w = np.asarray(w, np.float32)
    flat = w.reshape(w.shape[0], -1)
    amax = np.max(np.abs(flat), axis=1)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    wq = np.clip(np.round(flat / scale[:, None]), -127, 127).astype(np.int8)
    return wq.reshape(w.shape), scale


def _parse_params(node):
    out = {}
    for k, v in node.get("param", {}).items():
        try:
            out[k] = ast.literal_eval(v)
        except (ValueError, SyntaxError):
            out[k] = v
    return out


def _eligible(node, exclude):
    if node["op"] not in _QUANTIZABLE or node["name"] in exclude:
        return False
    if node["op"] == "Convolution":
        p = _parse_params(node)
        if p.get("num_group", 1) not in (1,):
            return False
        d = p.get("dilate")
        if d and tuple(d) != (1,) * len(tuple(d)):
            return False
    return True


def _calibrate(symbol, arg_params, aux_params, taps, calib_data,
               num_batches, data_name):
    """Max-abs activation calibration: bind the FLOAT net's internals so
    each target layer's INPUT activation is observed on real batches;
    ``taps`` maps layer name -> POSITIONAL internal-output index.
    Returns {layer_name: act_scale}."""
    internals = symbol.get_internals()
    names = list(taps)
    group = sym_mod.Group([internals[int(taps[n])] for n in names])

    amax = {n: 0.0 for n in names}
    exes = {}  # batch shape -> bound executor (ragged final batches)
    seen = 0
    for batch in calib_data:
        if seen >= num_batches:
            break
        # DataBatch carries .data as a list; a raw numpy array also has
        # a .data attribute (its memoryview), so duck-type carefully
        data = (batch.data[0]
                if isinstance(getattr(batch, "data", None), (list, tuple))
                else batch)
        arr = data.asnumpy() if isinstance(data, nd.NDArray) \
            else np.asarray(data, np.float32)
        exe = exes.get(arr.shape)
        if exe is None:
            exe = group.simple_bind(cpu_ctx(), grad_req="null",
                                    **{data_name: arr.shape})
            for k, v in arg_params.items():
                if k in exe.arg_dict and k != data_name:
                    exe.arg_dict[k][:] = v
            for k, v in (aux_params or {}).items():
                if k in exe.aux_dict:
                    exe.aux_dict[k][:] = v
            exes[arr.shape] = exe
        exe.arg_dict[data_name][:] = arr
        outs = exe.forward(is_train=False)
        for n, out in zip(names, outs):
            amax[n] = max(amax[n], float(np.max(np.abs(out.asnumpy()))))
        seen += 1
    if seen == 0:
        raise MXNetError("quantize_model: calib_data yielded no batches")
    return {n: (a / 127.0 if a > 0 else 1.0) for n, a in amax.items()}


def quantize_model(symbol, arg_params, aux_params=None, calib_data=None,
                   num_calib_batches=5, exclude=(), data_name="data"):
    """Rewrite ``symbol`` + params for int8 inference.

    Returns ``(qsym, qarg_params, qaux_params)``.  With ``calib_data``
    (a DataIter or iterable of array batches) the quantized layers also
    carry calibrated activation scales (full-int8 contractions);
    without it they run the weight-only dequant path.
    """
    if isinstance(exclude, str):
        exclude = (exclude,)  # a bare string must not degrade to chars
    exclude = set(exclude)
    conf = json.loads(symbol.tojson())
    nodes = conf["nodes"]

    # layer -> POSITIONAL index of the internal output feeding its data
    # input (the calibration tap).  Indexing internals by position —
    # tojson emits nodes in the same topo order get_internals walks, one
    # entry per op output — avoids name collisions (e.g. an RNN's
    # 'rnn_state' output vs its 'rnn_state' initial-state variable).
    # Resolution only happens — and can only raise — on the calibrated
    # path.
    from ..ops import OP_REGISTRY

    targets = [node for node in nodes
               if _eligible(node, exclude)
               and node["name"] + "_weight" in arg_params]
    taps = {}
    if calib_data is not None:
        offsets, total = [], 0
        for node in nodes:
            offsets.append(total)
            if node["op"] == "null":
                total += 1
            else:
                op = OP_REGISTRY.get(node["op"])
                total += op.num_outputs(op.make_params(node.get("param",
                                                                {})))
        n_internal = len(symbol.get_internals().list_outputs())
        if total != n_internal:
            raise MXNetError(
                f"quantize_model: internal-output count mismatch "
                f"({total} vs {n_internal})")
        for node in targets:
            src_idx, out_idx = node["inputs"][0][0], node["inputs"][0][1]
            taps[node["name"]] = offsets[src_idx] + out_idx

    act_scales = {}
    if calib_data is not None and taps:
        act_scales = _calibrate(symbol, arg_params, aux_params, taps,
                                calib_data, num_calib_batches, data_name)

    qargs = {k: (v if isinstance(v, nd.NDArray) else nd.array(v))
             for k, v in arg_params.items()}
    # rebuild the node list in topological order: each quantized layer's
    # wscale variable must appear BEFORE its consumer, so indices shift
    # and every reference is remapped through old -> new
    target_names = {node["name"] for node in targets}
    new_nodes = []
    remap = {}
    for old_idx, node in enumerate(nodes):
        name = node["name"]
        if name in target_names:
            w = qargs.pop(name + "_weight")
            wq, scale = quantize_weight(w.asnumpy())
            qargs[name + "_weight"] = nd.array(wq, dtype=np.int8)
            qargs[name + "_wscale"] = nd.array(scale)
            new_nodes.append({"op": "null", "name": name + "_wscale",
                              "inputs": []})
            scale_idx = len(new_nodes) - 1

            node = dict(node)
            node["op"] = _QUANTIZABLE[node["op"]]
            param = {k: v for k, v in node.get("param", {}).items()
                     if k not in _CONV_DROP}
            if name in act_scales:
                param["act_scale"] = repr(act_scales[name])
            node["param"] = param
            inputs = [[remap[i], oi] + rest
                      for i, oi, *rest in node["inputs"]]
            node["inputs"] = (inputs[:2] + [[scale_idx, 0]] + inputs[2:])
        else:
            node = dict(node)
            node["inputs"] = [[remap[i], oi] + rest
                              for i, oi, *rest in node["inputs"]]
        new_nodes.append(node)
        remap[old_idx] = len(new_nodes) - 1

    conf["nodes"] = new_nodes
    conf["heads"] = [[remap[i], oi] + rest
                     for i, oi, *rest in conf.get("heads", [])]
    conf["arg_nodes"] = [i for i, n in enumerate(new_nodes)
                         if n["op"] == "null"]
    qsym = sym_mod.load_json(json.dumps(conf))
    return qsym, qargs, dict(aux_params or {})
