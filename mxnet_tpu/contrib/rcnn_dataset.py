"""Detection dataset layer for the Faster R-CNN toolkit: image
databases, Pascal VOC loading, and VOC mAP evaluation.

Capability rebuild of the reference's rcnn dataset helpers —
``/root/reference/example/rcnn/helper/dataset/imdb.py`` (IMDB roidb
construction / flipping / recall evaluation),
``pascal_voc.py`` (VOC devkit layout, XML ground truth, results
writing, eval driver) and ``voc_eval.py`` (per-class AP with the
07/ integral metrics) — with the repo's conventions: dense numpy
``gt_overlaps`` instead of scipy sparse matrices, ``.npz`` proposal
files instead of MATLAB ``.mat`` selective-search blobs, and logging
instead of prints.  Geometry comes from ``contrib.rcnn``
(bbox_overlaps).
"""

from __future__ import annotations

import logging
import os
import pickle
import xml.etree.ElementTree as ET

import numpy as np

from .rcnn import bbox_overlaps

__all__ = ["IMDB", "PascalVOC", "parse_voc_rec", "voc_ap", "voc_eval"]

log = logging.getLogger(__name__)


class IMDB:
    """General image database: an ordered image-set index plus roidb
    records ``{'boxes', 'gt_classes', 'gt_overlaps', 'flipped'}``
    (reference imdb.py:13-106)."""

    def __init__(self, name):
        self.name = name
        self.classes = []
        self.image_set_index = []
        self.config = {}

    @property
    def num_classes(self):
        return len(self.classes)

    @property
    def num_images(self):
        return len(self.image_set_index)

    def image_path_from_index(self, index):
        raise NotImplementedError

    def gt_roidb(self):
        raise NotImplementedError

    def create_roidb_from_box_list(self, box_list, gt_roidb):
        """Proposal boxes -> roidb records, scoring each box by its best
        IoU against the ground truth of its class (imdb.py:31-64)."""
        if len(box_list) != self.num_images:
            raise ValueError("box_list length must match number of images")
        roidb = []
        for i in range(self.num_images):
            boxes = np.asarray(box_list[i], dtype=np.float64).reshape(-1, 4)
            overlaps = np.zeros((boxes.shape[0], self.num_classes),
                                dtype=np.float32)
            if gt_roidb is not None and gt_roidb[i]["boxes"].size > 0:
                gt_boxes = gt_roidb[i]["boxes"].astype(np.float64)
                gt_classes = gt_roidb[i]["gt_classes"]
                ious = bbox_overlaps(boxes, gt_boxes)
                argmaxes = ious.argmax(axis=1)
                maxes = ious.max(axis=1)
                pos = np.where(maxes > 0)[0]
                overlaps[pos, gt_classes[argmaxes[pos]]] = maxes[pos]
            roidb.append({"boxes": boxes,
                          "gt_classes": np.zeros(boxes.shape[0], np.int32),
                          "gt_overlaps": overlaps,
                          "flipped": False})
        return roidb

    @staticmethod
    def merge_roidbs(a, b):
        """Concatenate per-image records (gt + proposals in one roidb)."""
        if len(a) != len(b):
            raise ValueError("roidbs must cover the same images")
        for i in range(len(a)):
            a[i]["boxes"] = np.vstack((a[i]["boxes"], b[i]["boxes"]))
            a[i]["gt_classes"] = np.hstack((a[i]["gt_classes"],
                                            b[i]["gt_classes"]))
            a[i]["gt_overlaps"] = np.vstack((a[i]["gt_overlaps"],
                                             b[i]["gt_overlaps"]))
        return a

    def image_width(self, index):
        """Image width for flipping; subclasses may override to avoid
        decoding (VOC reads it from the annotation XML)."""
        from ..cv import imdecode

        with open(self.image_path_from_index(index), "rb") as f:
            return imdecode(f.read()).shape[1]

    def append_flipped_images(self, roidb):
        """Double the roidb with x-mirrored box records; images flip at
        load time (imdb.py:80-106)."""
        if self.num_images != len(roidb):
            raise ValueError("roidb does not cover the image set")
        widths = [self.image_width(idx) for idx in self.image_set_index]
        for i in range(len(widths)):
            boxes = roidb[i]["boxes"].copy()
            oldx1 = boxes[:, 0].copy()
            oldx2 = boxes[:, 2].copy()
            boxes[:, 0] = widths[i] - oldx2 - 1
            boxes[:, 2] = widths[i] - oldx1 - 1
            if not (boxes[:, 2] >= boxes[:, 0]).all():
                raise ValueError("flipped boxes degenerate")
            roidb.append({"boxes": boxes,
                          "gt_classes": roidb[i]["gt_classes"],
                          "gt_overlaps": roidb[i]["gt_overlaps"],
                          "flipped": True})
        self.image_set_index = list(self.image_set_index) * 2
        return roidb

    def evaluate_recall(self, roidb, candidate_boxes=None, thresholds=None,
                        limit=None):
        """Proposal recall across IoU thresholds (imdb.py:108-186):
        greedily matches each gt to its best-covering proposal and
        reports recall@t plus the average recall."""
        gt_overlaps = np.zeros(0)
        num_pos = 0
        for i in range(len(roidb)):
            max_gt = roidb[i]["gt_overlaps"].max(axis=1) \
                if roidb[i]["gt_overlaps"].size else np.zeros(0)
            gt_inds = np.where((roidb[i]["gt_classes"] > 0)
                               & (max_gt == 1))[0]
            gt_boxes = roidb[i]["boxes"][gt_inds]
            num_pos += len(gt_inds)
            if candidate_boxes is None:
                boxes = roidb[i]["boxes"][roidb[i]["gt_classes"] == 0]
            else:
                boxes = candidate_boxes[i]
            if boxes.shape[0] == 0 or gt_boxes.shape[0] == 0:
                continue
            if limit is not None:
                boxes = boxes[:limit]
            ious = bbox_overlaps(boxes.astype(np.float64),
                                 gt_boxes.astype(np.float64))
            covered = np.zeros(gt_boxes.shape[0])
            for _ in range(gt_boxes.shape[0]):
                gt_ind = ious.max(axis=0).argmax()
                box_ind = ious[:, gt_ind].argmax()
                covered[gt_ind] = ious[box_ind, gt_ind]
                ious[box_ind, :] = -1
                ious[:, gt_ind] = -1
            gt_overlaps = np.hstack((gt_overlaps, covered))
        if thresholds is None:
            thresholds = np.arange(0.5, 0.95 + 1e-5, 0.05)
        recalls = np.array([(gt_overlaps >= t).sum() / max(num_pos, 1)
                            for t in thresholds])
        return {"ar": recalls.mean(), "recalls": recalls,
                "thresholds": np.asarray(thresholds),
                "gt_overlaps": np.sort(gt_overlaps)}

    def evaluate_detections(self, detections):
        raise NotImplementedError


# --------------------------------------------------------------- VOC eval
def parse_voc_rec(filename):
    """Parse one Pascal VOC annotation XML into object dicts
    (voc_eval.py:10-29)."""
    objects = []
    for obj in ET.parse(filename).findall("object"):
        bbox = obj.find("bndbox")
        diff = obj.find("difficult")
        objects.append({
            "name": obj.find("name").text.strip(),
            "difficult": int(diff.text) if diff is not None else 0,
            "bbox": [int(float(bbox.find(t).text))
                     for t in ("xmin", "ymin", "xmax", "ymax")]})
    return objects


def voc_ap(rec, prec, use_07_metric=False):
    """Average precision: the 11-point VOC07 metric or the exact
    precision-envelope integral (voc_eval.py:32-64)."""
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = prec[rec >= t].max() if (rec >= t).any() else 0.0
            ap += p / 11.0
        return ap
    mrec = np.concatenate(([0.0], rec, [1.0]))
    mpre = np.concatenate(([0.0], prec, [0.0]))
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = max(mpre[i - 1], mpre[i])
    idx = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[idx + 1] - mrec[idx]) * mpre[idx + 1]))


def voc_eval(detpath, annopath, imageset_file, classname, cache_dir,
             ovthresh=0.5, use_07_metric=False):
    """Per-class PASCAL VOC evaluation -> (recall, precision, ap)
    (voc_eval.py:67-176): detections ranked by confidence, greedy IoU
    matching against non-difficult ground truth, double detections count
    as false positives."""
    os.makedirs(cache_dir, exist_ok=True)
    cache_file = os.path.join(cache_dir, "annotations.pkl")
    with open(imageset_file) as f:
        image_ids_all = [x.strip() for x in f if x.strip()]

    if os.path.isfile(cache_file):
        with open(cache_file, "rb") as f:
            recs = pickle.load(f)
    else:
        recs = {i: parse_voc_rec(annopath.format(i)) for i in image_ids_all}
        with open(cache_file, "wb") as f:
            pickle.dump(recs, f)

    class_recs = {}
    npos = 0
    for image_id in image_ids_all:
        objs = [o for o in recs[image_id] if o["name"] == classname]
        bbox = np.array([o["bbox"] for o in objs]).reshape(-1, 4)
        difficult = np.array([o["difficult"] for o in objs], bool)
        npos += int((~difficult).sum())
        class_recs[image_id] = {"bbox": bbox, "difficult": difficult,
                                "det": [False] * len(objs)}

    with open(detpath.format(classname)) as f:
        lines = [x.strip().split(" ") for x in f if x.strip()]
    image_ids = [x[0] for x in lines]
    confidence = np.array([float(x[1]) for x in lines])
    bb_all = np.array([[float(z) for z in x[2:]] for x in lines]) \
        .reshape(-1, 4)

    order = np.argsort(-confidence)
    bb_all = bb_all[order]
    image_ids = [image_ids[i] for i in order]

    nd = len(image_ids)
    tp, fp = np.zeros(nd), np.zeros(nd)
    for d in range(nd):
        rec_d = class_recs[image_ids[d]]
        bb = bb_all[d]
        ovmax, jmax = -np.inf, -1
        gt = rec_d["bbox"]
        if gt.size:
            ixmin = np.maximum(gt[:, 0], bb[0])
            iymin = np.maximum(gt[:, 1], bb[1])
            ixmax = np.minimum(gt[:, 2], bb[2])
            iymax = np.minimum(gt[:, 3], bb[3])
            iw = np.maximum(ixmax - ixmin + 1.0, 0.0)
            ih = np.maximum(iymax - iymin + 1.0, 0.0)
            inter = iw * ih
            union = ((bb[2] - bb[0] + 1.0) * (bb[3] - bb[1] + 1.0)
                     + (gt[:, 2] - gt[:, 0] + 1.0)
                     * (gt[:, 3] - gt[:, 1] + 1.0) - inter)
            ious = inter / union
            jmax = int(ious.argmax())
            ovmax = float(ious.max())
        if ovmax > ovthresh:
            if not rec_d["difficult"][jmax]:
                if not rec_d["det"][jmax]:
                    tp[d] = 1.0
                    rec_d["det"][jmax] = True
                else:
                    fp[d] = 1.0  # double detection
        else:
            fp[d] = 1.0

    fp = np.cumsum(fp)
    tp = np.cumsum(tp)
    rec = tp / max(npos, 1)
    prec = tp / np.maximum(tp + fp, np.finfo(np.float64).eps)
    return rec, prec, voc_ap(rec, prec, use_07_metric)


# --------------------------------------------------------------- PascalVOC
VOC_CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor")


class PascalVOC(IMDB):
    """Pascal VOC image database over the standard VOCdevkit layout
    (reference pascal_voc.py): XML ground truth, external proposals
    (``.npz`` with one array per image, replacing the reference's
    selective-search ``.mat``), results writing and mAP evaluation."""

    def __init__(self, image_set, year, root_path, devkit_path,
                 classes=VOC_CLASSES):
        super().__init__("voc_" + year + "_" + image_set)
        self.image_set = image_set
        self.year = year
        self.root_path = root_path
        self.devkit_path = devkit_path
        self.data_path = os.path.join(devkit_path, "VOC" + year)
        self.classes = list(classes)
        self.config = {"comp_id": "comp4", "use_diff": False,
                       "min_size": 2}
        self.image_set_index = self._load_image_set_index()

    @property
    def cache_path(self):
        path = os.path.join(self.root_path, "cache")
        os.makedirs(path, exist_ok=True)
        return path

    def _load_image_set_index(self):
        path = os.path.join(self.data_path, "ImageSets", "Main",
                            self.image_set + ".txt")
        with open(path) as f:
            return [x.strip() for x in f if x.strip()]

    def image_path_from_index(self, index):
        path = os.path.join(self.data_path, "JPEGImages", index + ".jpg")
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        return path

    def image_width(self, index):
        """VOC annotations carry the image size — no decode needed."""
        xml = os.path.join(self.data_path, "Annotations", index + ".xml")
        size = ET.parse(xml).getroot().find("size")
        if size is not None:
            return int(size.find("width").text)
        return super().image_width(index)

    def gt_roidb(self):
        cache_file = os.path.join(self.cache_path, self.name + "_gt_roidb.pkl")
        if os.path.exists(cache_file):
            with open(cache_file, "rb") as f:
                return pickle.load(f)
        roidb = [self._load_annotation(i) for i in self.image_set_index]
        with open(cache_file, "wb") as f:
            pickle.dump(roidb, f)
        return roidb

    def _load_annotation(self, index):
        filename = os.path.join(self.data_path, "Annotations",
                                index + ".xml")
        objs = parse_voc_rec(filename)
        if not self.config["use_diff"]:
            objs = [o for o in objs if not o["difficult"]]
        boxes = np.zeros((len(objs), 4), np.float64)
        gt_classes = np.zeros(len(objs), np.int32)
        overlaps = np.zeros((len(objs), self.num_classes), np.float32)
        cls_index = {c: i for i, c in enumerate(self.classes)}
        for ix, obj in enumerate(objs):
            boxes[ix] = [v - 1 for v in obj["bbox"]]  # 0-based pixels
            cls = cls_index[obj["name"].lower().strip()]
            gt_classes[ix] = cls
            overlaps[ix, cls] = 1.0
        return {"boxes": boxes, "gt_classes": gt_classes,
                "gt_overlaps": overlaps, "flipped": False}

    def proposal_roidb(self, gt_roidb, proposals_file):
        """gt + external proposals merged into one training roidb
        (the reference's selective_search_roidb / rpn_roidb shape;
        proposals come from an ``.npz`` holding one (n_i, 4) array per
        image index)."""
        data = np.load(proposals_file, allow_pickle=True)
        box_list = []
        for index in self.image_set_index:
            boxes = np.asarray(data[index], np.float64).reshape(-1, 4)
            keep = _unique_boxes(boxes)
            boxes = boxes[keep]
            boxes = boxes[_filter_small(boxes, self.config["min_size"])]
            box_list.append(boxes)
        roidb = self.create_roidb_from_box_list(box_list, gt_roidb)
        if self.image_set != "test" and gt_roidb is not None:
            roidb = IMDB.merge_roidbs(gt_roidb, roidb)
        return roidb

    # -- evaluation ---------------------------------------------------------
    def _result_file(self, cls):
        folder = os.path.join(self.devkit_path, "results",
                              "VOC" + self.year, "Main")
        os.makedirs(folder, exist_ok=True)
        name = (self.config["comp_id"] + "_det_" + self.image_set
                + "_{:s}.txt")
        return os.path.join(folder, name).format(cls)

    def write_pascal_results(self, all_boxes):
        """``all_boxes[cls][image]`` = (n, 5) [x1 y1 x2 y2 score] arrays
        -> one devkit-format results file per class (1-based pixels)."""
        for cls_ind, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            with open(self._result_file(cls), "w") as f:
                for im_ind, index in enumerate(self.image_set_index):
                    dets = np.asarray(all_boxes[cls_ind][im_ind])
                    for k in range(dets.shape[0] if dets.size else 0):
                        f.write(
                            "{:s} {:.3f} {:.1f} {:.1f} {:.1f} {:.1f}\n"
                            .format(index, dets[k, -1], dets[k, 0] + 1,
                                    dets[k, 1] + 1, dets[k, 2] + 1,
                                    dets[k, 3] + 1))

    def do_python_eval(self, ovthresh=0.5):
        """Per-class AP + mAP over the written results files; the VOC
        metric switched from 11-point to integral in 2010."""
        annopath = os.path.join(self.data_path, "Annotations", "{0!s}.xml")
        imageset_file = os.path.join(self.data_path, "ImageSets", "Main",
                                     self.image_set + ".txt")
        use_07 = int(self.year) < 2010
        aps = {}
        for cls in self.classes:
            if cls == "__background__":
                continue
            _, _, ap = voc_eval(self._result_file("{:s}"), annopath,
                                imageset_file, cls,
                                os.path.join(self.cache_path, self.name),
                                ovthresh=ovthresh, use_07_metric=use_07)
            aps[cls] = ap
            log.info("AP for %s = %.4f", cls, ap)
        mean_ap = float(np.mean(list(aps.values()))) if aps else 0.0
        log.info("Mean AP = %.4f", mean_ap)
        return aps, mean_ap

    def evaluate_detections(self, detections):
        self.write_pascal_results(detections)
        return self.do_python_eval()


def _unique_boxes(boxes, scale=1.0):
    """Indices of first occurrences (reference bbox_process.unique_boxes)."""
    v = np.array([1, 1e3, 1e6, 1e9])
    hashes = np.round(boxes * scale).dot(v)
    _, index = np.unique(hashes, return_index=True)
    return np.sort(index)


def _filter_small(boxes, min_size):
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    return np.where((ws >= min_size) & (hs >= min_size))[0]
