"""Region-proposal detection toolkit (Faster R-CNN family).

Capability rebuild of the reference ``example/rcnn`` support stack:
anchor enumeration (helper/processing/generate_anchor.py), bbox
regression transforms and clipping (bbox_transform.py), greedy NMS
(nms.py), RPN anchor-target assignment (rcnn/minibatch.py
assign_anchor), and the two CustomOps of the end-to-end trainer —
``Proposal`` (rcnn/rpn/proposal.py) and ``ProposalTarget``
(rcnn/rpn/proposal_target.py).

All box math uses the reference's inclusive pixel convention
(width = x2 - x1 + 1).  Proposal generation runs host-side through the
CustomOp bridge, exactly where the reference runs it (these are
data-dependent, dynamically-shaped steps that do not belong inside an
XLA program); the dense compute around them (backbone, RPN heads,
ROIPooling head) stays on the TPU.
"""

from __future__ import annotations

import numpy as np

from .. import operator as op_mod


# ----------------------------------------------------------------- anchors
def generate_anchors(base_size=16, ratios=(0.5, 1, 2), scales=(8, 16, 32)):
    """Enumerate ratio × scale anchor windows around a base_size box
    anchored at (0, 0) (generate_anchor.py semantics)."""
    base = np.array([0, 0, base_size - 1, base_size - 1], np.float64)
    w, h, cx, cy = _whctrs(base)
    size = w * h
    out = []
    for r in ratios:
        ws = np.round(np.sqrt(size / r))
        hs = np.round(ws * r)
        for s in np.asarray(scales, np.float64):
            out.append(_mkanchor(ws * s, hs * s, cx, cy))
    # reference stacks scale-major within each ratio
    return np.asarray(out, np.float64)


def _whctrs(anchor):
    w = anchor[2] - anchor[0] + 1
    h = anchor[3] - anchor[1] + 1
    return w, h, anchor[0] + 0.5 * (w - 1), anchor[1] + 0.5 * (h - 1)


def _mkanchor(w, h, cx, cy):
    return [cx - 0.5 * (w - 1), cy - 0.5 * (h - 1),
            cx + 0.5 * (w - 1), cy + 0.5 * (h - 1)]


def shift_anchors(base_anchors, feat_h, feat_w, feat_stride):
    """All anchors over a (feat_h, feat_w) grid: (H*W*A, 4), row-major
    over positions, anchor-major within a position."""
    sx = np.arange(feat_w) * feat_stride
    sy = np.arange(feat_h) * feat_stride
    gx, gy = np.meshgrid(sx, sy)
    shifts = np.stack([gx.ravel(), gy.ravel(), gx.ravel(), gy.ravel()],
                      axis=1)
    all_anchors = (base_anchors[None, :, :]
                   + shifts[:, None, :].astype(np.float64))
    return all_anchors.reshape(-1, 4)


# ------------------------------------------------------------ bbox algebra
def bbox_transform(ex_rois, gt_rois):
    """Regression targets (dx, dy, dw, dh) taking ex_rois onto gt_rois."""
    ew = ex_rois[:, 2] - ex_rois[:, 0] + 1.0
    eh = ex_rois[:, 3] - ex_rois[:, 1] + 1.0
    ecx = ex_rois[:, 0] + 0.5 * (ew - 1.0)
    ecy = ex_rois[:, 1] + 0.5 * (eh - 1.0)
    gw = gt_rois[:, 2] - gt_rois[:, 0] + 1.0
    gh = gt_rois[:, 3] - gt_rois[:, 1] + 1.0
    gcx = gt_rois[:, 0] + 0.5 * (gw - 1.0)
    gcy = gt_rois[:, 1] + 0.5 * (gh - 1.0)
    return np.stack([(gcx - ecx) / (ew + 1e-14),
                     (gcy - ecy) / (eh + 1e-14),
                     np.log(gw / ew), np.log(gh / eh)], axis=1)


def bbox_pred(boxes, deltas):
    """Apply (dx, dy, dw, dh) deltas to boxes; deltas may carry 4 columns
    per class ((N, 4k) -> (N, 4k))."""
    if boxes.shape[0] == 0:
        return np.zeros((0, deltas.shape[1]), deltas.dtype)
    boxes = boxes.astype(np.float64)
    w = boxes[:, 2] - boxes[:, 0] + 1.0
    h = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (w - 1.0)
    cy = boxes[:, 1] + 0.5 * (h - 1.0)
    dx, dy = deltas[:, 0::4], deltas[:, 1::4]
    dw, dh = deltas[:, 2::4], deltas[:, 3::4]
    pcx = dx * w[:, None] + cx[:, None]
    pcy = dy * h[:, None] + cy[:, None]
    pw = np.exp(dw) * w[:, None]
    ph = np.exp(dh) * h[:, None]
    out = np.zeros_like(deltas, dtype=np.float64)
    out[:, 0::4] = pcx - 0.5 * (pw - 1.0)
    out[:, 1::4] = pcy - 0.5 * (ph - 1.0)
    out[:, 2::4] = pcx + 0.5 * (pw - 1.0)
    out[:, 3::4] = pcy + 0.5 * (ph - 1.0)
    return out


def clip_boxes(boxes, im_shape):
    """Clip (N, 4k) boxes to an (h, w) image, inclusive convention."""
    h, w = im_shape[:2]
    boxes = boxes.copy()
    boxes[:, 0::4] = np.clip(boxes[:, 0::4], 0, w - 1)
    boxes[:, 1::4] = np.clip(boxes[:, 1::4], 0, h - 1)
    boxes[:, 2::4] = np.clip(boxes[:, 2::4], 0, w - 1)
    boxes[:, 3::4] = np.clip(boxes[:, 3::4], 0, h - 1)
    return boxes


def bbox_overlaps(boxes, query):
    """IoU matrix (N, K) in the inclusive convention."""
    if boxes.size == 0 or query.size == 0:
        return np.zeros((boxes.shape[0], query.shape[0]))
    b_area = ((boxes[:, 2] - boxes[:, 0] + 1)
              * (boxes[:, 3] - boxes[:, 1] + 1))[:, None]
    q_area = ((query[:, 2] - query[:, 0] + 1)
              * (query[:, 3] - query[:, 1] + 1))[None, :]
    iw = (np.minimum(boxes[:, None, 2], query[None, :, 2])
          - np.maximum(boxes[:, None, 0], query[None, :, 0]) + 1)
    ih = (np.minimum(boxes[:, None, 3], query[None, :, 3])
          - np.maximum(boxes[:, None, 1], query[None, :, 1]) + 1)
    inter = np.clip(iw, 0, None) * np.clip(ih, 0, None)
    return inter / (b_area + q_area - inter)


def nms(dets, thresh):
    """Greedy IoU suppression over (N, 5) [x1 y1 x2 y2 score]; returns
    kept indices in score order (nms.py)."""
    if dets.shape[0] == 0:
        return []
    boxes, scores = dets[:, :4], dets[:, 4]
    order = scores.argsort()[::-1]
    keep = []
    while order.size:
        i = order[0]
        keep.append(int(i))
        if order.size == 1:
            break
        ious = bbox_overlaps(boxes[i:i + 1], boxes[order[1:]])[0]
        order = order[1:][ious <= thresh]
    return keep


# ---------------------------------------------------------- RPN targets
def assign_anchor(feat_shape, gt_boxes, im_info, feat_stride=16,
                  scales=(8, 16, 32), ratios=(0.5, 1, 2),
                  allowed_border=0, batch_rois=256, fg_fraction=0.5,
                  fg_overlap=0.7, bg_overlap=0.3, rng=None):
    """RPN training targets (minibatch.py assign_anchor): per-anchor
    labels (1 fg / 0 bg / -1 ignore), bbox targets and weights, sampled
    to ``batch_rois`` with at most ``fg_fraction`` positives.

    Returns dict with 'label' (A*H*W,), 'bbox_target' and 'bbox_weight'
    (A*H*W, 4) in anchor-major-within-position order.
    """
    rng = rng or np.random
    feat_h, feat_w = feat_shape[-2:]
    base = generate_anchors(base_size=feat_stride, ratios=ratios,
                            scales=scales)
    A = base.shape[0]
    all_anchors = shift_anchors(base, feat_h, feat_w, feat_stride)
    total = all_anchors.shape[0]
    im_h, im_w = im_info[0], im_info[1]
    inside = np.where(
        (all_anchors[:, 0] >= -allowed_border)
        & (all_anchors[:, 1] >= -allowed_border)
        & (all_anchors[:, 2] < im_w + allowed_border)
        & (all_anchors[:, 3] < im_h + allowed_border))[0]
    anchors = all_anchors[inside]

    labels = np.full(len(inside), -1, np.float64)
    if len(inside) == 0:
        # no anchor fits the image (anchors larger than the image):
        # everything is ignored rather than crashing downstream argmax
        return {"label": np.full(total, -1, np.float64),
                "bbox_target": np.zeros((total, 4)),
                "bbox_weight": np.zeros((total, 4))}
    if gt_boxes.size:
        overlaps = bbox_overlaps(anchors, gt_boxes[:, :4])
        argmax = overlaps.argmax(axis=1)
        max_o = overlaps[np.arange(len(inside)), argmax]
        gt_argmax = overlaps.argmax(axis=0)
        # expand to ALL anchors tied at each gt's max overlap (reference
        # minibatch.py: np.where(overlaps == gt_max_overlaps)) — ties are
        # common on a symmetric anchor grid and every one is foreground
        gt_max = overlaps[gt_argmax, np.arange(overlaps.shape[1])]
        gt_argmax = np.where(overlaps == gt_max)[0]
        labels[max_o < bg_overlap] = 0
        labels[gt_argmax] = 1          # best anchor(s) per gt always fg
        labels[max_o >= fg_overlap] = 1
    else:
        labels[:] = 0

    # subsample to the roi batch
    fg_cap = int(fg_fraction * batch_rois)
    fg = np.where(labels == 1)[0]
    if len(fg) > fg_cap:
        labels[rng.choice(fg, len(fg) - fg_cap, replace=False)] = -1
    bg_cap = batch_rois - int((labels == 1).sum())
    bg = np.where(labels == 0)[0]
    if len(bg) > bg_cap:
        labels[rng.choice(bg, len(bg) - bg_cap, replace=False)] = -1

    targets = np.zeros((len(inside), 4))
    if gt_boxes.size:
        targets = bbox_transform(anchors, gt_boxes[argmax, :4])
    weights = np.zeros((len(inside), 4))
    weights[labels == 1, :] = 1.0

    def unmap(data, fill):
        out = np.full((total,) + data.shape[1:], fill, np.float64)
        out[inside] = data
        return out

    return {"label": unmap(labels, -1),
            "bbox_target": unmap(targets, 0),
            "bbox_weight": unmap(weights, 0)}


# ------------------------------------------------------------- custom ops
class ProposalOp(op_mod.CustomOp):
    """rois from RPN outputs: decode deltas at every anchor, clip,
    filter tiny boxes, top-pre_nms by score, NMS, top-post_nms
    (rcnn/rpn/proposal.py)."""

    def __init__(self, feat_stride, scales, ratios, rpn_pre_nms_top_n,
                 rpn_post_nms_top_n, nms_thresh, rpn_min_size):
        self._stride = feat_stride
        self._anchors = generate_anchors(base_size=feat_stride,
                                         ratios=ratios, scales=scales)
        self._pre = rpn_pre_nms_top_n
        self._post = rpn_post_nms_top_n
        self._thresh = nms_thresh
        self._min_size = rpn_min_size
        self._rng = np.random.RandomState(0)  # pad-sampling RNG

    def forward(self, is_train, req, in_data, out_data, aux):
        scores = np.asarray(in_data[0])   # (1, 2A, H, W) softmax probs
        deltas = np.asarray(in_data[1])   # (1, 4A, H, W)
        im_info = np.asarray(in_data[2]).reshape(-1)  # (h, w, scale)
        A = self._anchors.shape[0]
        H, W = scores.shape[-2:]
        fg = scores[0, A:]                               # (A, H, W)
        fg = fg.transpose(1, 2, 0).reshape(-1)           # pos-major
        d = deltas[0].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        anchors = shift_anchors(self._anchors, H, W, self._stride)
        boxes = bbox_pred(anchors, d)
        boxes = clip_boxes(boxes, im_info[:2])
        min_size = self._min_size * im_info[2]
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        keep = np.where((ws >= min_size) & (hs >= min_size))[0]
        boxes, fg = boxes[keep], fg[keep]
        order = fg.argsort()[::-1][:self._pre]
        boxes, fg = boxes[order], fg[order]
        keep = nms(np.hstack([boxes, fg[:, None]]), self._thresh)[:self._post]
        boxes, fg = boxes[keep], fg[keep]
        # fixed-size output: pad a short set by randomly re-sampling kept
        # rois (reference proposal.py npr.choice pad) so downstream
        # ProposalTarget sampling is not biased toward the top roi
        n_out = out_data[0].shape[0]
        if boxes.shape[0] == 0:
            boxes = np.zeros((1, 4))
            fg = np.zeros(1)
        if boxes.shape[0] >= n_out:
            idx = np.arange(n_out)
        else:
            idx = np.concatenate([
                np.arange(boxes.shape[0]),
                self._rng.choice(boxes.shape[0],
                                 n_out - boxes.shape[0], replace=True)])
        rois = np.hstack([np.zeros((n_out, 1)), boxes[idx]])
        self.assign(out_data[0], req[0], rois.astype(np.float32))
        if len(out_data) > 1:
            self.assign(out_data[1], req[1],
                        fg[idx, None].astype(np.float32))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for i, g in enumerate(in_grad):
            self.assign(g, req[i], 0.0)


@op_mod.register("proposal")
class ProposalProp(op_mod.CustomOpProp):
    def __init__(self, feat_stride=16, scales="(8, 16, 32)",
                 ratios="(0.5, 1, 2)", rpn_pre_nms_top_n=6000,
                 rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
                 output_score=False):
        super().__init__(need_top_grad=False)
        import ast

        def _tup(v):
            return tuple(ast.literal_eval(v) if isinstance(v, str) else v)

        self._kw = dict(
            feat_stride=int(feat_stride),
            scales=_tup(scales),
            ratios=_tup(ratios),
            rpn_pre_nms_top_n=int(rpn_pre_nms_top_n),
            rpn_post_nms_top_n=int(rpn_post_nms_top_n),
            nms_thresh=float(threshold), rpn_min_size=int(rpn_min_size))
        self._output_score = (output_score in (True, "True", "true", "1"))

    def list_arguments(self):
        return ["cls_prob", "bbox_pred", "im_info"]

    def list_outputs(self):
        return ["output", "score"] if self._output_score else ["output"]

    def infer_shape(self, in_shape):
        n = self._kw["rpn_post_nms_top_n"]
        outs = [[n, 5]] + ([[n, 1]] if self._output_score else [])
        return in_shape, outs, []

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalOp(**self._kw)


class ProposalTargetOp(op_mod.CustomOp):
    """Sample proposals into a head ROI batch with labels and per-class
    bbox targets (rcnn/rpn/proposal_target.py): gt boxes join the
    candidate set, fg_fraction capped by >=fg_overlap IoU."""

    def __init__(self, num_classes, batch_rois, fg_fraction, fg_overlap,
                 bg_overlap_hi, seed):
        self._nc = num_classes
        self._batch = batch_rois
        self._fg_frac = fg_fraction
        self._fg_ov = fg_overlap
        self._bg_hi = bg_overlap_hi
        self._rng = np.random.RandomState(seed)

    def forward(self, is_train, req, in_data, out_data, aux):
        rois = np.asarray(in_data[0])[:, 1:5]
        gt = np.asarray(in_data[1])          # (G, 5) x1 y1 x2 y2 cls
        gt = gt[gt[:, :4].sum(axis=1) > 0]
        cand = np.vstack([rois, gt[:, :4]]) if gt.size else rois
        overlaps = bbox_overlaps(cand, gt[:, :4]) if gt.size else \
            np.zeros((cand.shape[0], 0))
        if gt.size:
            argmax = overlaps.argmax(axis=1)
            max_o = overlaps[np.arange(cand.shape[0]), argmax]
            labels = gt[argmax, 4]
        else:
            max_o = np.zeros(cand.shape[0])
            labels = np.zeros(cand.shape[0])
        fg = np.where(max_o >= self._fg_ov)[0]
        bg = np.where(max_o < self._bg_hi)[0]
        n_fg = min(int(self._fg_frac * self._batch), len(fg))
        if len(fg) > n_fg:
            fg = self._rng.choice(fg, n_fg, replace=False)
        n_bg = self._batch - n_fg
        if len(bg) > n_bg:
            bg = self._rng.choice(bg, n_bg, replace=False)
        sel = np.append(fg, bg).astype(np.int64)
        n_pad = self._batch - sel.size
        pad_is_fg = False
        if n_pad > 0:
            # pad from the bg pool so repeated rois never carry
            # contradictory labels; fall back to fg (keeping their true
            # class) only when there is no bg at all
            if len(bg):
                pad_src = np.asarray(bg, np.int64)
            elif len(fg):
                pad_src = np.asarray(fg, np.int64)
                pad_is_fg = True
            else:
                pad_src = np.zeros(1, np.int64)
            sel = np.append(sel, np.resize(pad_src, n_pad))
        keep = sel[:self._batch]
        fg_mask = np.zeros(self._batch, bool)
        fg_mask[:len(fg)] = True
        if pad_is_fg:
            fg_mask[len(fg) + len(bg):] = True
        labels = labels[keep].copy()
        labels[~fg_mask] = 0
        sampled = cand[keep]
        targets = np.zeros((self._batch, 4 * self._nc))
        weights = np.zeros((self._batch, 4 * self._nc))
        if gt.size:
            t = bbox_transform(sampled, gt[argmax[keep], :4])
            for i in np.where(fg_mask)[0]:
                c = int(labels[i])
                targets[i, 4 * c:4 * c + 4] = t[i]
                weights[i, 4 * c:4 * c + 4] = 1.0
        out_rois = np.hstack([np.zeros((self._batch, 1)), sampled])
        self.assign(out_data[0], req[0], out_rois.astype(np.float32))
        self.assign(out_data[1], req[1], labels.astype(np.float32))
        self.assign(out_data[2], req[2], targets.astype(np.float32))
        self.assign(out_data[3], req[3], weights.astype(np.float32))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        for i, g in enumerate(in_grad):
            self.assign(g, req[i], 0.0)


@op_mod.register("proposal_target")
class ProposalTargetProp(op_mod.CustomOpProp):
    def __init__(self, num_classes=21, batch_rois=128, fg_fraction=0.25,
                 fg_overlap=0.5, bg_overlap_hi=0.5, seed=0):
        super().__init__(need_top_grad=False)
        self._nc = int(num_classes)
        self._batch = int(batch_rois)
        self._kw = dict(num_classes=self._nc, batch_rois=self._batch,
                        fg_fraction=float(fg_fraction),
                        fg_overlap=float(fg_overlap),
                        bg_overlap_hi=float(bg_overlap_hi), seed=int(seed))

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_output", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        b, nc = self._batch, self._nc
        return in_shape, [[b, 5], [b], [b, 4 * nc], [b, 4 * nc]], []

    def create_operator(self, ctx, shapes, dtypes):
        return ProposalTargetOp(**self._kw)


# ------------------------------------------------------------- inference
def im_detect(rois, cls_prob, bbox_deltas, im_shape, score_thresh=0.05,
              nms_thresh=0.3, max_per_class=100):
    """Decode head outputs into per-class detections
    (rcnn/tester.py pred_eval inner loop + detector.py im_detect).

    rois        : (N, 5) [batch_idx x1 y1 x2 y2] from the proposal op
    cls_prob    : (N, C) softmax over classes (class 0 = background)
    bbox_deltas : (N, 4C) per-class regression deltas
    im_shape    : (h, w) for clipping
    Returns {class_index: (K, 5) [x1 y1 x2 y2 score]} for classes >= 1.
    """
    rois = np.asarray(rois, np.float64)
    cls_prob = np.asarray(cls_prob, np.float64)
    bbox_deltas = np.asarray(bbox_deltas, np.float64)
    if rois.shape[1] == 5 and rois[:, 0].max(initial=0) > 0:
        # like the reference tester (single-image batches only): refuse
        # rather than cross-image-NMS a multi-image roi set
        raise ValueError(
            "im_detect decodes one image at a time; split the rois by "
            "their batch_idx column first")
    boxes = bbox_pred(rois[:, 1:5], bbox_deltas)
    boxes = clip_boxes(boxes, im_shape)
    dets = {}
    for c in range(1, cls_prob.shape[1]):
        scores = cls_prob[:, c]
        keep = np.where(scores > score_thresh)[0]
        if keep.size == 0:
            dets[c] = np.zeros((0, 5))
            continue
        cls_boxes = boxes[keep, 4 * c:4 * c + 4]
        cls_dets = np.hstack([cls_boxes, scores[keep, None]])
        keep_nms = nms(cls_dets, nms_thresh)[:max_per_class]
        dets[c] = cls_dets[keep_nms]
    return dets
