"""Contributed higher-level components built on the public API.

``rcnn`` — region-proposal detection toolkit (anchors, bbox regression,
NMS, RPN target assignment, Proposal/ProposalTarget custom ops): the
capability surface of the reference ``example/rcnn`` helper/rpn stack.

``rcnn_dataset`` — the dataset/eval layer on top: IMDB/PascalVOC image
databases and VOC mAP evaluation (reference example/rcnn/helper/dataset).
"""

from . import quantization
from . import rcnn
from . import rcnn_dataset

__all__ = ["quantization", "rcnn", "rcnn_dataset"]
