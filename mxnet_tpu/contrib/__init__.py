"""Contributed higher-level components built on the public API.

``rcnn`` — region-proposal detection toolkit (anchors, bbox regression,
NMS, RPN target assignment, Proposal/ProposalTarget custom ops): the
capability surface of the reference ``example/rcnn`` helper/rpn stack.
"""

from . import rcnn

__all__ = ["rcnn"]
