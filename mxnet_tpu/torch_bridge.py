"""PyTorch interop bridge.

Rebuild of the reference torch plugin (plugin/torch/torch_module-inl.h,
torch_criterion-inl.h, python/mxnet/torch.py): run torch modules,
criterions and functions inside the graph or eagerly over NDArrays.
The reference embedded Lua Torch via TH/THC; the living equivalent is
PyTorch (CPU), executed as host callbacks (``jax.pure_callback``)
around the compiled XLA program — the same mechanics as CustomOp.

A wrapped module's learnable parameters surface as op *arguments*
(named ``<name>_param_i``), so framework optimizers/initializers manage
them exactly like native layer weights — mirroring how TorchModule
exposed Lua module weights to the MXNet optimizer.
"""

from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from .operator import CustomOp, CustomOpProp, register

__all__ = ["TorchModule", "TorchCriterion", "torch_function"]


def _import_torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover
        raise MXNetError("torch_bridge requires pytorch") from e
    return torch


class TorchModule:
    """Wrap a ``torch.nn.Module`` as a symbolic operator.

    >>> net = TorchModule(torch.nn.Linear(8, 4), name="tlin")(data_sym)

    The module runs on host CPU; its parameters are op arguments
    (initialized from the module's current values via the
    ``init_params`` helper or any framework initializer).
    """

    def __init__(self, module, name=None):
        self.module = module
        self.name = name or f"torch_{type(module).__name__.lower()}"
        self._param_tensors = list(module.parameters())
        self._registered = None

    def param_names(self):
        return [f"{self.name}_param_{i}"
                for i in range(len(self._param_tensors))]

    def init_values(self):
        """Current torch parameter values, keyed by op argument name —
        feed to Module.init_params(arg_params=...) or set_params."""
        return {n: p.detach().cpu().numpy()
                for n, p in zip(self.param_names(), self._param_tensors)}

    def _infer_out_shapes(self, in_shape):
        torch = _import_torch()
        with torch.no_grad():
            out = self.module(torch.zeros(*in_shape))
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return [tuple(o.shape) for o in outs]

    def _ensure_registered(self):
        if self._registered:
            return self._registered
        bridge = self
        reg_name = f"_torch_module_{self.name}_{id(self):x}"

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=True)

            def list_arguments(self):
                # bare names; symbol naming prefixes them with the op
                # instance name, yielding bridge.param_names()
                return ["data"] + [f"param_{i}"
                                   for i in range(len(bridge._param_tensors))]

            def list_outputs(self):
                return ["output"]

            def infer_shape(self, in_shape):
                data_shape = in_shape[0]
                param_shapes = [tuple(p.shape)
                                for p in bridge._param_tensors]
                out_shapes = bridge._infer_out_shapes(data_shape)
                return [tuple(data_shape)] + param_shapes, out_shapes, []

            def create_operator(self, ctx, shapes, dtypes):
                return _TorchModuleOp(bridge)

        register(reg_name)(_Prop)
        self._registered = reg_name
        return reg_name

    def __call__(self, data, name=None):
        from . import symbol as sym_mod

        reg_name = self._ensure_registered()
        fn = getattr(sym_mod, reg_name)
        return fn(data=data, name=name or self.name)


class _TorchModuleOp(CustomOp):
    def __init__(self, bridge):
        self.bridge = bridge

    def _load_params(self, torch, in_data):
        with torch.no_grad():
            for p, v in zip(self.bridge._param_tensors, in_data[1:]):
                p.copy_(torch.from_numpy(np.ascontiguousarray(v)))

    def forward(self, is_train, req, in_data, out_data, aux):
        torch = _import_torch()
        self._load_params(torch, in_data)
        with torch.no_grad():
            out = self.bridge.module(torch.from_numpy(
                np.ascontiguousarray(in_data[0])))
        out = out if isinstance(out, (tuple, list)) else (out,)
        for dst, o in zip(out_data, out):
            self.assign(dst, req[0], o.detach().cpu().numpy())

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        torch = _import_torch()
        self._load_params(torch, in_data)
        x = torch.from_numpy(np.ascontiguousarray(in_data[0]))
        x.requires_grad_(True)
        params = self.bridge._param_tensors
        for p in params:
            p.requires_grad_(True)
            p.grad = None
        out = self.bridge.module(x)
        out.backward(torch.from_numpy(np.ascontiguousarray(out_grad[0])))
        grads = [x.grad] + [p.grad for p in params]
        for dst, g, r in zip(in_grad, grads, req):
            self.assign(dst, r, np.zeros_like(dst) if g is None
                        else g.detach().cpu().numpy())


class TorchCriterion:
    """Wrap a torch loss (criterion) as an output layer
    (torch_criterion-inl.h): forward emits the scalar loss broadcast per
    batch row; backward injects d(loss)/d(data), ignoring head grads."""

    def __init__(self, criterion, name=None):
        self.criterion = criterion
        self.name = name or f"torch_{type(criterion).__name__.lower()}"
        self._registered = None

    def _ensure_registered(self):
        if self._registered:
            return self._registered
        bridge = self
        reg_name = f"_torch_criterion_{self.name}_{id(self):x}"

        class _Prop(CustomOpProp):
            def __init__(self):
                super().__init__(need_top_grad=False)

            def list_arguments(self):
                return ["data", "label"]

            def list_outputs(self):
                return ["loss"]

            def infer_shape(self, in_shape):
                return [tuple(s) for s in in_shape], [(in_shape[0][0],)], []

            def create_operator(self, ctx, shapes, dtypes):
                return _TorchCriterionOp(bridge)

        register(reg_name)(_Prop)
        self._registered = reg_name
        return reg_name

    def __call__(self, data, label, name=None):
        from . import symbol as sym_mod

        fn = getattr(sym_mod, self._ensure_registered())
        return fn(data=data, label=label, name=name or self.name)


class _TorchCriterionOp(CustomOp):
    def __init__(self, bridge):
        self.bridge = bridge

    def _loss(self, torch, in_data, need_grad):
        x = torch.from_numpy(np.ascontiguousarray(in_data[0]))
        y = torch.from_numpy(np.ascontiguousarray(in_data[1]))
        if need_grad:
            x.requires_grad_(True)
        crit = self.bridge.criterion
        target = y.long() if _is_class_criterion(crit) else y
        loss = crit(x, target)
        return x, loss

    def forward(self, is_train, req, in_data, out_data, aux):
        torch = _import_torch()
        with torch.no_grad():
            _, loss = self._loss(torch, in_data, need_grad=False)
        val = float(loss.detach().cpu().numpy())
        self.assign(out_data[0], req[0],
                    np.full(out_data[0].shape, val, out_data[0].dtype))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        torch = _import_torch()
        x, loss = self._loss(torch, in_data, need_grad=True)
        loss.backward()
        self.assign(in_grad[0], req[0], x.grad.detach().cpu().numpy())
        self.assign(in_grad[1], req[1], np.zeros_like(in_grad[1]))


def _is_class_criterion(crit):
    name = type(crit).__name__
    return name in ("CrossEntropyLoss", "NLLLoss")


def torch_function(fn, *args, **kwargs):
    """Eagerly apply a torch function to NDArrays (python/mxnet/torch.py
    function dispatch): NDArray → torch CPU tensor → fn → NDArray."""
    torch = _import_torch()

    def conv(v):
        if isinstance(v, NDArray):
            return torch.from_numpy(v.asnumpy())
        return v

    out = fn(*[conv(a) for a in args],
             **{k: conv(v) for k, v in kwargs.items()})
    if isinstance(out, (tuple, list)):
        return [nd.array(o.detach().cpu().numpy()) for o in out]
    return nd.array(out.detach().cpu().numpy())
