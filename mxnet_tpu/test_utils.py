"""Testing utilities.

Rebuild of python/mxnet/test_utils.py: ``check_numeric_gradient`` (random
projections + central finite differences, reference test_utils.py:270),
``check_symbolic_forward/backward``, ``check_consistency`` (same symbol
across contexts/dtypes, test_utils.py:616), ``check_speed``, and data
helpers.
"""

from __future__ import annotations

import time

import numpy as np

from . import context as ctx_mod
from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError

__all__ = ["default_context", "set_default_context", "default_dtype",
           "default_numerical_threshold", "rand_ndarray", "random_arrays",
           "np_reduce", "check_numeric_gradient",
           "check_symbolic_forward", "check_symbolic_backward",
           "check_consistency", "check_speed", "reldiff", "same",
           "almost_equal", "assert_almost_equal", "simple_forward"]


def default_context():
    return ctx_mod.current_context()


def set_default_context(ctx):
    """Set the default context (reference test_utils.py:24)."""
    ctx_mod.Context._default_ctx.value = ctx


def default_dtype():
    """Default dtype for regression tests (reference test_utils.py:28)."""
    return np.float32


def default_numerical_threshold():
    """Default numerical tolerance (reference test_utils.py:34)."""
    return 1e-6


def random_arrays(*shapes):
    """Random numpy arrays, one per shape; a lone shape returns the bare
    array (reference test_utils.py:41)."""
    arrays = [np.random.randn(*s).astype(default_dtype()) for s in shapes]
    if len(arrays) == 1:
        return arrays[0]
    return arrays


def np_reduce(dat, axis, keepdims, numpy_reduce_func):
    """Reduce over (possibly multiple) axes with optional kept dims
    (reference test_utils.py:50) — the comparison twin for reduce ops."""
    if isinstance(axis, int):
        axis = [axis]
    else:
        axis = list(axis) if axis is not None else range(len(dat.shape))
    ret = dat
    for i in reversed(sorted(axis)):
        ret = numpy_reduce_func(ret, axis=i)
    if keepdims:
        keepdims_shape = list(dat.shape)
        for i in axis:
            keepdims_shape[i] = 1
        ret = ret.reshape(tuple(keepdims_shape))
    return ret


def same(a, b):
    return np.array_equal(a, b)


def reldiff(a, b):
    diff = np.abs(a - b).sum()
    norm = (np.abs(a) + np.abs(b)).sum()
    return diff / norm if norm != 0 else diff


def almost_equal(a, b, threshold=None):
    """True when two arrays agree within reldiff threshold (reference
    test_utils.py:111)."""
    threshold = threshold or default_numerical_threshold()
    rel = reldiff(np.asarray(a), np.asarray(b))
    return not np.isnan(rel) and rel <= threshold


def assert_almost_equal(a, b, threshold=1e-5, rtol=None, atol=None):
    if rtol is not None or atol is not None:
        np.testing.assert_allclose(a, b, rtol=rtol or 1e-5, atol=atol or 1e-20)
        return
    rd = reldiff(np.asarray(a), np.asarray(b))
    if rd > threshold:
        raise AssertionError(f"reldiff {rd} > {threshold}")


def rand_ndarray(shape, ctx=None, scale=1.0):
    return nd.array(np.random.uniform(-scale, scale, shape), ctx=ctx)


def _parse_location(sym, location, ctx):
    if isinstance(location, dict):
        return {k: (v if isinstance(v, nd.NDArray) else nd.array(v, ctx=ctx))
                for k, v in location.items()}
    return {name: (v if isinstance(v, nd.NDArray) else nd.array(v, ctx=ctx))
            for name, v in zip(sym.list_arguments(), location)}


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Forward a symbol on given numpy inputs, returning numpy outputs."""
    ctx = ctx or default_context()
    shapes = {k: v.shape for k, v in inputs.items()}
    exe = sym.simple_bind(ctx, grad_req="null", **shapes)
    for k, v in inputs.items():
        exe.arg_dict[k][:] = v
    outs = [o.asnumpy() for o in exe.forward(is_train=is_train)]
    return outs[0] if len(outs) == 1 else outs


def numeric_grad(executor, location, aux_states=None, eps=1e-4,
                 use_forward_train=True):
    """Central finite differences over every arg (test_utils.py:270)."""
    approx_grads = {}
    for name, arr in location.items():
        base = arr.asnumpy().copy()
        grad = np.zeros_like(base, dtype=np.float64)
        flat = base.ravel()
        gflat = grad.ravel()
        for i in range(flat.size):
            old = flat[i]
            flat[i] = old + eps
            executor.arg_dict[name][:] = base.reshape(arr.shape)
            fp = _total_out(executor, use_forward_train)
            flat[i] = old - eps
            executor.arg_dict[name][:] = base.reshape(arr.shape)
            fm = _total_out(executor, use_forward_train)
            flat[i] = old
            executor.arg_dict[name][:] = base.reshape(arr.shape)
            gflat[i] = (fp - fm) / (2 * eps)
        approx_grads[name] = grad
    return approx_grads


def _total_out(executor, is_train):
    outs = executor.forward(is_train=is_train)
    return sum(float(o.asnumpy().sum()) for o in outs)


def check_numeric_gradient(sym, location, aux_states=None, numeric_eps=1e-3,
                           check_eps=1e-2, grad_nodes=None, use_forward_train=True,
                           ctx=None, proj=None):
    """Compare symbolic gradients of sum(outputs·proj) against finite
    differences (reference test_utils.py check_numeric_gradient)."""
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    if grad_nodes is None:
        grad_nodes = list(location.keys())

    # random projection makes the scalar objective sensitive everywhere
    input_shapes = {k: v.shape for k, v in location.items()}
    _, out_shapes, _ = sym.infer_shape(**input_shapes)
    proj_syms = []
    out_grouped = sym if len(sym.list_outputs()) > 1 else sym_mod.Group([sym])
    heads = []
    for i, oshape in enumerate(out_shapes):
        p = sym_mod.Variable(f"__random_proj_{i}")
        heads.append(sym_mod.MakeLoss(sym_mod.sum(out_grouped[i] * p)))
    combined = sym_mod.Group(heads)

    proj_arrays = {f"__random_proj_{i}": nd.array(
        np.random.normal(0, 1.0, s), ctx=ctx)
        for i, s in enumerate(out_shapes)}
    grad_req = {k: ("write" if k in grad_nodes else "null")
                for k in list(location) + list(proj_arrays)}
    all_args = {**location, **proj_arrays}
    shapes = {k: v.shape for k, v in all_args.items()}
    exe = combined.simple_bind(ctx, grad_req=grad_req, **shapes)
    for k, v in all_args.items():
        exe.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k][:] = v
    exe.forward(is_train=True)
    exe.backward()
    symbolic_grads = {k: exe.grad_dict[k].asnumpy() for k in grad_nodes}

    numeric_gradients = numeric_grad(
        exe, {k: v for k, v in location.items() if k in grad_nodes},
        eps=numeric_eps, use_forward_train=use_forward_train)

    for name in grad_nodes:
        fd_grad = numeric_gradients[name]
        sym_grad = symbolic_grads[name]
        rd = reldiff(fd_grad, sym_grad)
        if rd > check_eps:
            raise AssertionError(
                f"numeric gradient check failed for {name}: reldiff {rd:.3g} "
                f"> {check_eps}\nnumeric:\n{fd_grad}\nsymbolic:\n{sym_grad}")


def check_symbolic_forward(sym, location, expected, check_eps=1e-5, ctx=None,
                           aux_states=None):
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    shapes = {k: v.shape for k, v in location.items()}
    exe = sym.simple_bind(ctx, grad_req="null", **shapes)
    for k, v in location.items():
        exe.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k][:] = v
    outputs = [o.asnumpy() for o in exe.forward(is_train=False)]
    for out, exp in zip(outputs, expected):
        if reldiff(out, np.asarray(exp)) > check_eps:
            raise AssertionError(
                f"forward check failed: reldiff > {check_eps}\n{out}\nvs\n{exp}")
    return outputs


def check_symbolic_backward(sym, location, out_grads, expected, check_eps=1e-5,
                            grad_req="write", ctx=None, aux_states=None):
    ctx = ctx or default_context()
    location = _parse_location(sym, location, ctx)
    shapes = {k: v.shape for k, v in location.items()}
    exe = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
    for k, v in location.items():
        exe.arg_dict[k][:] = v
    if aux_states:
        for k, v in aux_states.items():
            exe.aux_dict[k][:] = v
    exe.forward(is_train=True)
    exe.backward([g if isinstance(g, nd.NDArray) else nd.array(g, ctx=ctx)
                  for g in out_grads])
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(sym.list_arguments(), expected))
    grads = {k: exe.grad_dict[k].asnumpy() for k in expected}
    for name, exp in expected.items():
        if reldiff(grads[name], np.asarray(exp)) > check_eps:
            raise AssertionError(
                f"backward check failed for {name}\n{grads[name]}\nvs\n{exp}")
    return grads


def check_consistency(sym, ctx_list, scale=1.0, type_dict=None,
                      arg_params=None, tol=None):
    """Run the same symbol across context/dtype configs and compare
    forward/backward (reference test_utils.py:616)."""
    tol = tol or {np.dtype(np.float16): 1e-1, np.dtype(np.float32): 1e-3,
                  np.dtype(np.float64): 1e-5}
    exe_list = []
    for spec in ctx_list:
        spec = dict(spec)
        ctx = spec.pop("ctx", default_context())
        dtypes = spec.pop("type_dict", type_dict or {})
        shapes = spec
        exe = sym.simple_bind(ctx, grad_req="write", type_dict=dtypes, **shapes)
        exe_list.append(exe)
    # identical inputs everywhere (cast per executor dtype)
    ref = exe_list[0]
    inits = {}
    for name, arr in ref.arg_dict.items():
        inits[name] = np.random.normal(0, scale, arr.shape)
        if arg_params and name in arg_params:
            inits[name] = arg_params[name]
    outputs = []
    grads = []
    for exe in exe_list:
        for name, v in inits.items():
            exe.arg_dict[name][:] = v.astype(exe.arg_dict[name].dtype)
        exe.forward(is_train=True)
        exe.backward()
        outputs.append([o.asnumpy().astype(np.float64) for o in exe.outputs])
        grads.append({k: g.asnumpy().astype(np.float64)
                      for k, g in exe.grad_dict.items()})
    for i, exe in enumerate(exe_list[1:], 1):
        t = tol.get(np.dtype(exe.arg_arrays[0].dtype), 1e-3)
        for o_ref, o in zip(outputs[0], outputs[i]):
            if reldiff(o_ref, o) > t:
                raise AssertionError(f"forward inconsistency in config {i}")
        for name in grads[0]:
            if reldiff(grads[0][name], grads[i][name]) > t:
                raise AssertionError(
                    f"backward inconsistency for {name} in config {i}")
    return outputs


def check_speed(sym, location=None, ctx=None, N=20, grad_req="write",
                typ="whole", **kwargs):
    """Micro-benchmark a symbol (reference test_utils.py:538)."""
    ctx = ctx or default_context()
    if location is None:
        location = {k: np.random.normal(size=s)
                    for k, s in kwargs.items()}
        shapes = kwargs
    else:
        shapes = {k: v.shape for k, v in location.items()}
    exe = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
    for k, v in location.items():
        exe.arg_dict[k][:] = v
    if typ == "whole":
        exe.forward(is_train=True)
        exe.backward()
        [o.wait_to_read() for o in exe.outputs]
        tic = time.perf_counter()
        for _ in range(N):
            exe.forward(is_train=True)
            exe.backward()
        for o in exe.outputs:
            o.wait_to_read()
        nd.waitall()
        return (time.perf_counter() - tic) / N
    elif typ == "forward":
        exe.forward(is_train=False)
        [o.wait_to_read() for o in exe.outputs]
        tic = time.perf_counter()
        for _ in range(N):
            exe.forward(is_train=False)
        for o in exe.outputs:
            o.wait_to_read()
        return (time.perf_counter() - tic) / N
    raise ValueError("typ must be 'whole' or 'forward'")
