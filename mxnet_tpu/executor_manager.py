"""Legacy data-parallel executor manager (reference
python/mxnet/executor_manager.py:276-424).

``DataParallelExecutorManager`` is the engine FeedForward-era training
loops drove directly: slice a batch over devices, fan forward/backward
out to per-device executors, aggregate metrics, and copy weights back.
Here it is a thin adapter over the Module-era
``DataParallelExecutorGroup`` (module/executor_group.py) — one
implementation, both API generations — with ``sym_gen`` bucketing
support backed by shared-memory executor binding (``shared_group``).
"""

from __future__ import annotations

import logging


from .io import DataDesc
from .module.executor_group import (DataParallelExecutorGroup,
                                    _split_input_slice)

__all__ = ["DataParallelExecutorManager", "_split_input_slice"]


class DataParallelExecutorManager:
    """Helper managing multiple executors for data parallelism
    (reference executor_manager.py:276).

    Parameters mirror the reference: ``symbol``, ``ctx`` (device list),
    ``train_data`` (provides shapes + batch size), the name lists, an
    optional ``work_load_list``, and ``sym_gen`` for bucketing.
    """

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None, sym_gen=None):
        if logger is None:
            logger = logging
        num_device = len(ctx)
        logger.info("Start training with %s", str(ctx))
        if work_load_list is None:
            work_load_list = [1] * num_device
        if not isinstance(work_load_list, list) or \
                len(work_load_list) != num_device:
            raise ValueError("Invalid settings for work load.")
        self.arg_names = arg_names
        self.param_names = param_names
        self.aux_names = aux_names
        self.ctx = ctx
        self._work_load_list = work_load_list
        self._logger = logger

        self._data_shapes = list(train_data.provide_data)
        self._label_shapes = list(train_data.provide_label or [])
        self.execgrp = self._bind(symbol)
        # the slices the group actually computes for compute fan-out
        # (derived from provide_data layouts) are THE slices
        self.slices = self.execgrp.slices
        self.symbol = symbol
        self.sym_gen = sym_gen
        self.curr_execgrp = self.execgrp
        self._pending_batch = None
        if self.sym_gen is not None:
            self.execgrp_bucket = {
                train_data.default_bucket_key: self.execgrp}

    def _bind(self, symbol, data_shapes=None, label_shapes=None,
              shared_group=None):
        return DataParallelExecutorGroup(
            symbol, self.ctx, self._work_load_list,
            data_shapes or self._data_shapes,
            label_shapes if label_shapes is not None else self._label_shapes,
            self.param_names, for_training=True, inputs_need_grad=False,
            shared_group=shared_group, logger=self._logger)

    def install_monitor(self, monitor):
        """Install monitor on all executors (reference :332-338)."""
        if self.sym_gen is not None:
            raise NotImplementedError(
                "Monitoring is not implemented for bucketing")
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        """Push parameter/aux values into every executor (:340-353)."""
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Device -> host master copy, averaged over devices (:355-374).
        Updates the passed NDArray dicts in place."""
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        """Per-parameter lists of per-device arrays (:376-380)."""
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        return self.execgrp.aux_arrays

    def load_data_batch(self, data_batch):
        """Stage a batch; with ``sym_gen``, lazily bind the batch's
        bucket sharing memory with the default bucket (:393-410)."""
        if self.sym_gen is not None:
            key = data_batch.bucket_key
            if key not in self.execgrp_bucket:
                symbol = self.sym_gen(key)
                provide = [DataDesc(*d) if not isinstance(d, DataDesc)
                           else d for d in data_batch.provide_data]
                provide_l = [DataDesc(*l) if not isinstance(l, DataDesc)
                             else l
                             for l in (data_batch.provide_label or [])]
                self.execgrp_bucket[key] = self._bind(
                    symbol, provide, provide_l, shared_group=self.execgrp)
            self.curr_execgrp = self.execgrp_bucket[key]
        else:
            self.curr_execgrp = self.execgrp
        # the group snapshots the arrays (the reference copies to device
        # at load): buffer-recycling pipelines can't leak mutations
        self.curr_execgrp.load_data_batch(data_batch)
        self._pending_batch = data_batch

    def forward(self, is_train=False):
        """Forward on the current executor group (:412-414) over the
        batch staged by ``load_data_batch``."""
        if self._pending_batch is None:
            raise ValueError("call load_data_batch before forward")
        self.curr_execgrp.forward(is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)
