"""Evaluation metrics (rebuild of python/mxnet/metric.py)."""

from __future__ import annotations

import numpy as _numpy
np = None  # rebound below: mx.metric.np is the CustomMetric factory (parity)

from .base import MXNetError
from .ndarray import NDArray
from .registry import Registry

__all__ = ["EvalMetric", "Accuracy", "TopKAccuracy", "F1", "MAE", "MSE", "RMSE",
           "CrossEntropy", "Loss", "CompositeEvalMetric", "CustomMetric", "np",
           "create"]

METRIC_REGISTRY = Registry("metric")


def _as_np(x):
    return x.asnumpy() if isinstance(x, NDArray) else _numpy.asarray(x)


def check_label_shapes(labels, preds, shape=False):
    if len(labels) != len(preds):
        raise MXNetError(f"label/pred count mismatch {len(labels)} vs {len(preds)}")


class EvalMetric:
    """Base metric with running (sum, count) state (metric.py:14-76).

    Device accumulation (opt-in via :meth:`device_accumulate`): metrics
    that define ``_device_update(label, pred) -> (sum, count)`` — a pure
    jax-traceable batch contribution — can keep their running state ON
    DEVICE, so the per-batch ``update_metric`` in the fit loop is one
    async jitted add instead of an ``asnumpy()`` pipeline stall.  Host
    ``sum_metric``/``num_inst`` only materialize at sync points: every
    ``frequent`` device updates, and lazily whenever :meth:`get` reads
    the value (so epoch-end logs and Speedometer callbacks are always
    correct)."""

    _device_update = None  # subclasses define (self, label, pred)->(s, n)

    def __init__(self, name, num=None):
        self.name = name
        self.num = num
        self._device_frequent = 0
        self._dev_state = None
        self._dev_pending = 0
        self._dev_fn = None
        self.reset()

    def reset(self):
        if self.num is None:
            self.num_inst = 0
            self.sum_metric = 0.0
        else:
            self.num_inst = [0] * self.num
            self.sum_metric = [0.0] * self.num
        self._dev_state = None
        self._dev_pending = 0

    def update(self, labels, preds):
        raise NotImplementedError

    # -- device accumulation ------------------------------------------------
    def device_accumulate(self, frequent=50):
        """Opt in to on-device (sum, count) accumulation, syncing to the
        host every ``frequent`` batches.  Returns True when this metric
        supports it (it defines ``_device_update`` and is single-valued);
        unsupported metrics return False and keep the host path.

        ``frequent=0`` (or any falsy value) switches BACK to host
        accumulation — any pending device contributions are folded in
        first, so no data is lost.  ``Module.fit`` sets the mode
        explicitly each run, so a metric instance reused across fits
        follows the current run's path, not a previous run's."""
        if not frequent:
            self._sync_device()
            self._device_frequent = 0
            return False
        if self.num is not None or self._device_update is None:
            return False
        self._device_frequent = max(1, int(frequent))
        return True

    @property
    def device_active(self):
        return self._device_frequent > 0 and self._device_update is not None

    def update_device(self, labels, preds):
        """Add one batch's contribution on device (one async jitted
        dispatch); host state updates only at the sync cadence."""
        import jax
        import jax.numpy as jnp

        if self._dev_fn is None:
            contrib = self._device_update

            def accum(ls, ps, acc):
                s, n = acc
                for label, pred in zip(ls, ps):
                    ds, dn = contrib(label, pred)
                    s = s + ds
                    n = n + dn
                return s, n

            self._dev_fn = jax.jit(accum)
        ls = [l._data if isinstance(l, NDArray) else jnp.asarray(l)
              for l in labels]
        ps = [p._data if isinstance(p, NDArray) else jnp.asarray(p)
              for p in preds]
        if self._dev_state is None:
            self._dev_state = (jnp.zeros((), jnp.float32),
                               jnp.zeros((), jnp.float32))
        self._dev_state = self._dev_fn(ls, ps, self._dev_state)
        self._dev_pending += 1
        if self._dev_pending >= self._device_frequent:
            self._sync_device()

    def _sync_device(self):
        """Fold the device accumulator into the host running state (the
        only point the metric path touches the host)."""
        if getattr(self, "_dev_state", None) is None:
            self._dev_pending = 0
            return
        s, n = self._dev_state
        self.sum_metric += float(s)
        # device counts are integral by construction; keep num_inst int
        # so host-path and device-path readings agree exactly
        self.num_inst += int(round(float(n)))
        self._dev_state = None
        self._dev_pending = 0

    def get(self):
        if getattr(self, "_dev_pending", 0):
            self._sync_device()
        if self.num is None:
            value = self.sum_metric / self.num_inst if self.num_inst else float("nan")
            return self.name, value
        names = [f"{self.name}_{i}" for i in range(self.num)]
        values = [s / n if n else float("nan")
                  for s, n in zip(self.sum_metric, self.num_inst)]
        return names, values

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            return [(name, value)]
        return list(zip(name, value))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


@METRIC_REGISTRY.register("acc", aliases=("accuracy",))
class Accuracy(EvalMetric):
    def __init__(self):
        super().__init__("accuracy")

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype(_numpy.int32)
            if pred.ndim > 1:
                pred = _numpy.argmax(pred, axis=-1).astype(_numpy.int32)
            else:
                pred = (pred > 0.5).astype(_numpy.int32)
            label = label.reshape(pred.shape)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += label.size

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        if pred.ndim > 1:
            p = jnp.argmax(pred, axis=-1).astype(jnp.int32)
        else:
            p = (pred > 0.5).astype(jnp.int32)
        l = label.astype(jnp.int32).reshape(p.shape)
        return (jnp.sum(p == l).astype(jnp.float32), jnp.float32(l.size))


@METRIC_REGISTRY.register("top_k_accuracy", aliases=("top_k_acc",))
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, **kwargs):
        self.top_k = kwargs.get("top_k", top_k)
        super().__init__(f"top_k_accuracy_{self.top_k}")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred = _as_np(pred)
            label = _as_np(label).astype(_numpy.int32)
            topk = _numpy.argsort(pred, axis=-1)[:, -self.top_k:]
            self.sum_metric += float((topk == label[:, None]).any(axis=1).sum())
            self.num_inst += label.shape[0]

    def _device_update(self, label, pred):
        import jax
        import jax.numpy as jnp

        _, topk = jax.lax.top_k(pred, self.top_k)
        l = label.astype(jnp.int32)
        hits = jnp.any(topk == l[:, None], axis=1)
        return (jnp.sum(hits).astype(jnp.float32), jnp.float32(l.shape[0]))


@METRIC_REGISTRY.register("f1")
class F1(EvalMetric):
    def __init__(self):
        super().__init__("f1")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            pred = _numpy.argmax(_as_np(pred), axis=-1)
            label = _as_np(label).astype(_numpy.int32).reshape(pred.shape)
            tp = float(((pred == 1) & (label == 1)).sum())
            fp = float(((pred == 1) & (label == 0)).sum())
            fn = float(((pred == 0) & (label == 1)).sum())
            precision = tp / (tp + fp) if tp + fp else 0.0
            recall = tp / (tp + fn) if tp + fn else 0.0
            f1 = (2 * precision * recall / (precision + recall)
                  if precision + recall else 0.0)
            self.sum_metric += f1
            self.num_inst += 1

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        p = jnp.argmax(pred, axis=-1)
        l = label.astype(jnp.int32).reshape(p.shape)
        tp = jnp.sum((p == 1) & (l == 1)).astype(jnp.float32)
        fp = jnp.sum((p == 1) & (l == 0)).astype(jnp.float32)
        fn = jnp.sum((p == 0) & (l == 1)).astype(jnp.float32)
        precision = jnp.where(tp + fp > 0, tp / (tp + fp), 0.0)
        recall = jnp.where(tp + fn > 0, tp / (tp + fn), 0.0)
        f1 = jnp.where(precision + recall > 0,
                       2 * precision * recall / (precision + recall), 0.0)
        return f1.astype(jnp.float32), jnp.float32(1)


@METRIC_REGISTRY.register("mae")
class MAE(EvalMetric):
    def __init__(self):
        super().__init__("mae")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(_numpy.abs(label.reshape(pred.shape) - pred).mean())
            self.num_inst += 1

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        err = jnp.mean(jnp.abs(label.reshape(pred.shape) - pred))
        return err.astype(jnp.float32), jnp.float32(1)


@METRIC_REGISTRY.register("mse")
class MSE(EvalMetric):
    def __init__(self):
        super().__init__("mse")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(((label.reshape(pred.shape) - pred) ** 2).mean())
            self.num_inst += 1

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        err = jnp.mean(jnp.square(label.reshape(pred.shape) - pred))
        return err.astype(jnp.float32), jnp.float32(1)


@METRIC_REGISTRY.register("rmse")
class RMSE(EvalMetric):
    def __init__(self):
        super().__init__("rmse")

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label, pred = _as_np(label), _as_np(pred)
            self.sum_metric += float(
                _numpy.sqrt(((label.reshape(pred.shape) - pred) ** 2).mean()))
            self.num_inst += 1

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        err = jnp.sqrt(jnp.mean(jnp.square(label.reshape(pred.shape) - pred)))
        return err.astype(jnp.float32), jnp.float32(1)


@METRIC_REGISTRY.register("ce", aliases=("cross-entropy",))
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-8):
        super().__init__("cross-entropy")
        self.eps = eps

    def update(self, labels, preds):
        for label, pred in zip(labels, preds):
            label = _as_np(label).ravel().astype(_numpy.int64)
            pred = _as_np(pred)
            prob = pred[_numpy.arange(label.shape[0]), label]
            self.sum_metric += float((-_numpy.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        l = label.ravel().astype(jnp.int32)
        prob = pred[jnp.arange(l.shape[0]), l]
        return (jnp.sum(-jnp.log(prob + self.eps)).astype(jnp.float32),
                jnp.float32(l.shape[0]))


@METRIC_REGISTRY.register("loss")
class Loss(EvalMetric):
    """Mean of raw outputs (for MakeLoss-style heads)."""

    def __init__(self):
        super().__init__("loss")

    def update(self, labels, preds):
        for pred in preds:
            pred = _as_np(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size

    def _device_update(self, label, pred):
        # Loss ignores labels; the device path still pairs label/pred
        # positionally, matching the host zip() truncation semantics
        import jax.numpy as jnp

        return jnp.sum(pred).astype(jnp.float32), jnp.float32(pred.size)


@METRIC_REGISTRY.register("torch")
class Torch(Loss):
    """Mean of external-framework criterion outputs (reference metric.py
    Torch/Caffe: both average the plugin loss op's raw outputs — e.g.
    losses produced through the torch bridge)."""

    def __init__(self, name="torch"):
        super().__init__()
        self.name = name

    def update(self, labels, preds):
        for pred in preds:
            pred = _as_np(pred)
            self.sum_metric += float(pred.mean())
            self.num_inst += 1

    def _device_update(self, label, pred):
        import jax.numpy as jnp

        return jnp.mean(pred).astype(jnp.float32), jnp.float32(1)


@METRIC_REGISTRY.register("caffe")
class Caffe(Torch):
    def __init__(self):
        super().__init__(name="caffe")


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, **kwargs):
        super().__init__("composite")
        self.metrics = [create(m) if isinstance(m, str) else m
                        for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(metric)

    def get_metric(self, index):
        """Child metric by position (reference metric.py:96)."""
        try:
            return self.metrics[index]
        except IndexError:
            raise ValueError(
                f"Metric index {index} is out of range 0 and "
                f"{len(self.metrics)}") from None

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.extend(n if isinstance(n, list) else [n])
            values.extend(v if isinstance(v, list) else [v])
        return names, values


class CustomMetric(EvalMetric):
    """Metric from a feval(label, pred) function (metric.py CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False):
        name = name or getattr(feval, "__name__", "custom")
        super().__init__(f"custom({name})")
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            reval = self._feval(_as_np(label), _as_np(pred))
            if isinstance(reval, tuple):
                s, n = reval
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function as a metric (metric.py np)."""
    return CustomMetric(numpy_feval, name, allow_extra_outputs)


def create(metric, **kwargs):
    if callable(metric):
        return CustomMetric(metric)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        return CompositeEvalMetric(metrics=[create(m, **kwargs) for m in metric])
    return METRIC_REGISTRY.get(metric)(**kwargs)
