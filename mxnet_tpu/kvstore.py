"""KVStore: parameter synchronization across devices and hosts.

Rebuild of the reference kvstore layer (include/mxnet/kvstore.h,
src/kvstore/{comm.h,kvstore_local.h,kvstore_dist.h}) with the transport
swapped for the TPU fabric (SURVEY.md §5 "Distributed communication
backend"):

- ``Comm`` is the reduce/broadcast engine.  ``CommCPU`` stages through
  host memory (the reference's pinned-staging tree-sum, comm.h:17-176);
  ``CommDevice`` reduces on-device — cross-chip transfers ride ICI via
  XLA device-to-device copies, standing in for CommDevice's CUDA P2P
  (comm.h:186-346).
- ``dist_*`` types replace the ps-lite parameter server with JAX
  multihost collectives over ICI/DCN (DistKVStore: rank/size/barrier map
  to process_index/process_count/sync_global_devices), or — when the
  launcher spawns server shards (``tools/launch.py -s N``) — with the
  host-side parameter server in mxnet_tpu/ps.py (DistPSKVStore), which
  restores true ``dist_async`` race semantics and the server-side
  optimizer (pickled to servers, reference kvstore.py:231-256).

API shape (init/push/pull with int or str keys, pluggable updater,
priority hints) matches python/mxnet/kvstore.py so Module/FeedForward
code ports unchanged.  On the device path XLA's async dispatch already
overlaps communication with compute; on the host PS path
(DistPSKVStore) pushes are STAGED on the dependency engine's
prioritized lane at the caller's priority (priority=-key orders sends
the way the next forward consumes weights — reference
python/mxnet/model.py:87-97), with per-key vars preserving
push-before-pull ordering.
"""

from __future__ import annotations

import pickle

import jax
import jax.numpy as jnp
import numpy as np

from . import context as _ctx
from . import ndarray as nd
from .base import MXNetError
from .ndarray import NDArray

__all__ = ["KVStore", "create"]


class Comm:
    """Reduce/broadcast primitive over a set of per-device arrays."""

    def __init__(self, reduce_ctx):
        self.reduce_ctx = reduce_ctx

    def reduce(self, arrays) -> NDArray:
        if len(arrays) == 1:
            return arrays[0].as_in_context(self.reduce_ctx)
        dev = self.reduce_ctx.jax_device()
        vals = [jax.device_put(a._data, dev) for a in arrays]
        return NDArray(self._tree_sum(vals), self.reduce_ctx)

    @staticmethod
    def _tree_sum(vals):
        """Pairwise (tree) summation: O(log n) dependency depth instead of
        a sequential add chain — the reference's chunked tree-sum
        (comm.h:17-176) shape, sized for pod-scale host staging."""
        while len(vals) > 1:
            nxt = [vals[i] + vals[i + 1] for i in range(0, len(vals) - 1, 2)]
            if len(vals) % 2:
                nxt.append(vals[-1])
            vals = nxt
        return vals[0]

    def broadcast(self, src: NDArray, dsts):
        for d in dsts:
            d._set(jax.device_put(src._data.astype(d.dtype), d._ctx.jax_device()))


class CommCPU(Comm):
    """Host-staged reduction (reference CommCPU)."""

    def __init__(self):
        super().__init__(_ctx.cpu_pinned(0))


class CommDevice(Comm):
    """On-device reduction: gather onto the first contributing device
    (reference balances placement, comm.h:307-334; XLA handles transfer
    scheduling here so we keep placement simple and deterministic)."""

    def __init__(self):
        super().__init__(None)

    def reduce(self, arrays) -> NDArray:
        target = arrays[0].context
        dev = target.jax_device()
        vals = [arrays[0]._data]
        vals += [jax.device_put(a._data, dev) for a in arrays[1:]]
        return NDArray(Comm._tree_sum(vals), target)


class KVStore:
    """Local key->value store (reference kvstore_local.h:22-127)."""

    def __init__(self, kind="local"):
        self._kind = kind
        if "device" in kind:
            self._comm = CommDevice()
        else:
            self._comm = CommCPU()
        self._store = {}
        self._updater = None
        self._optimizer = None

    # -- identity ----------------------------------------------------------
    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        return 0

    @property
    def num_workers(self):
        return 1

    def set_gradient_compression(self, compression_params):
        """Gradient compression is a dist-transport feature (the wire is
        what it shrinks); local stores reject it like the reference."""
        raise MXNetError(
            "gradient compression requires a dist kvstore "
            f"(this store is {self._kind!r})")

    # -- core --------------------------------------------------------------
    @staticmethod
    def _normalize(key, value):
        if isinstance(key, (int, str)):
            key, value = [key], [value]
        out = []
        for k, v in zip(key, value):
            vs = v if isinstance(v, (list, tuple)) else [v]
            out.append((k, list(vs)))
        return out

    def init(self, key, value):
        for k, vs in self._normalize(key, value):
            if k in self._store:
                raise MXNetError(f"key {k!r} already initialized")
            self._store[k] = vs[0].copyto(
                self._comm.reduce_ctx or vs[0].context)

    def push(self, key, value, priority=0):
        """Aggregate values into the store; run updater if installed
        (reference: Comm::Reduce then updater-or-assign)."""
        for k, vs in self._normalize(key, value):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            reduced = self._comm.reduce(vs)
            stored = self._store[k]
            if self._updater is not None:
                reduced = reduced.as_in_context(stored.context)
                self._updater(k, reduced, stored)
            else:
                stored._set(jax.device_put(
                    reduced._data.astype(stored.dtype),
                    stored._ctx.jax_device()))

    def pull(self, key, out=None, priority=0):
        for k, outs in self._normalize(key, out):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            self._comm.broadcast(self._store[k], outs)

    # -- updater / optimizer -------------------------------------------------
    def _set_updater(self, updater):
        self._updater = updater

    set_updater = _set_updater

    def dead_nodes(self, timeout=60.0):
        """Worker ranks whose heartbeat lapsed (empty for local stores;
        the PS-backed store reports real ranks).  The list form behind
        :meth:`num_dead_node`, surfaced so training loops can name the
        dead peers (``mx.callback.DeadNodeMonitor``)."""
        return []

    def num_dead_node(self, node_id=0, timeout=60.0):
        """Failure-detection hook (reference kvstore.h:235-244
        get_num_dead_node over ps-lite heartbeats); 0 for local stores."""
        return len(self.dead_nodes(timeout))

    def set_optimizer(self, optimizer):
        """Install an optimizer as the store-side updater.  In dist mode the
        reference pickles the optimizer to PS servers
        (python/mxnet/kvstore.py:231-256); here the updater always runs
        worker-side (no server tier on the TPU fabric) — pickling is kept
        to validate optimizer serializability for checkpoint parity."""
        from .optimizer import get_updater

        try:
            optimizer = pickle.loads(pickle.dumps(optimizer))
        except Exception as e:
            import logging

            # the optimizer still works in-process; checkpoint parity
            # across restarts is what just silently degraded — say so
            logging.warning("optimizer %s is not picklable (%s): "
                            "checkpoint/dist serialization will fall "
                            "back to the live object",
                            type(optimizer).__name__, e)
        self._optimizer = optimizer
        self._set_updater(get_updater(optimizer))

    # -- distributed hooks ----------------------------------------------------
    def barrier(self):
        pass

    def send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized")
        with open(fname, "wb") as f:
            f.write(pickle.dumps(getattr(self._updater, "states", {})))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("optimizer not initialized")
        with open(fname, "rb") as f:
            self._updater.states.update(pickle.loads(f.read()))


_DIST_INITIALIZED = False


def _maybe_init_distributed():
    """Join the multi-process rendezvous from tools/launch.py env vars
    (MXTPU_COORDINATOR / MXTPU_NUM_PROCS / MXTPU_PROC_ID) — the analog of
    the reference's DMLC_* tracker contract (tools/launch.py:33-50,
    kvstore_dist.h scheduler rendezvous).  No-op when the env is absent
    (single-process; jax.process_count() == 1) or already initialized."""
    global _DIST_INITIALIZED
    import os

    if _DIST_INITIALIZED or "MXTPU_COORDINATOR" not in os.environ:
        return
    from .base import env_int

    jax.distributed.initialize(
        coordinator_address=os.environ["MXTPU_COORDINATOR"],
        num_processes=env_int("MXTPU_NUM_PROCS", 1),
        process_id=env_int("MXTPU_PROC_ID", 0))
    _DIST_INITIALIZED = True


class DistKVStore(KVStore):
    """Multi-host store over JAX collectives (replaces kvstore_dist.h).

    ``set_gradient_compression`` (overridden below) is rejected with a
    pointer at the PS tier: this path's all-reduce rides ICI/DCN
    collectives inside XLA, where host-side 2-bit packing has no wire
    to shrink.

    Each host pushes its locally-reduced gradient; cross-host aggregation
    is an all-reduce over DCN/ICI via multihost allgather+sum.  Sync mode
    is inherent (collectives are synchronous across processes); true
    ``dist_async`` server-race semantics need the parameter-server tier
    (DistPSKVStore, selected when the launcher spawns servers with
    ``-s N``) — without servers async degrades to sync here.
    """

    def __init__(self, kind):
        _maybe_init_distributed()
        super().__init__(kind)
        self._nproc = jax.process_count()

    def set_gradient_compression(self, compression_params):
        raise MXNetError(
            "gradient compression applies to the parameter-server "
            "transport; this store aggregates via in-XLA collectives. "
            "Launch server shards (tools/launch.py -s N) to get the PS "
            "tier (DistPSKVStore), which supports it")

    def init(self, key, value):
        """Rank 0's initial values win everywhere (the reference PS
        contract: workers init to the server's — i.e. rank 0's — state,
        so all ranks start from identical weights; without this, each
        rank's own random init diverges the replicas permanently)."""
        super().init(key, value)
        if self._nproc > 1:
            from jax.experimental import multihost_utils

            for k, _ in self._normalize(key, value):
                stored = self._store[k]
                synced = multihost_utils.broadcast_one_to_all(stored._data)
                stored._set(jax.device_put(synced, stored._ctx.jax_device()))

    @property
    def rank(self):
        return jax.process_index()

    @property
    def num_workers(self):
        return self._nproc

    def push(self, key, value, priority=0):
        for k, vs in self._normalize(key, value):
            if k not in self._store:
                raise MXNetError(f"key {k!r} not initialized")
            reduced = self._comm.reduce(vs)
            if self._nproc > 1:
                from jax.experimental import multihost_utils

                gathered = multihost_utils.process_allgather(reduced._data)
                reduced = NDArray(jnp.sum(gathered, axis=0), reduced.context)
            stored = self._store[k]
            if self._updater is not None:
                reduced = reduced.as_in_context(stored.context)
                self._updater(k, reduced, stored)
            else:
                stored._set(jax.device_put(
                    reduced._data.astype(stored.dtype), stored._ctx.jax_device()))

    def barrier(self):
        if self._nproc > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("kvstore_barrier")


class DistPSKVStore(KVStore):
    """Parameter-server-backed distributed store (true ``dist_async``).

    Used when the launcher started server shards (``tools/launch.py -s N``
    sets ``MXTPU_PS_ADDRS``).  Reproduces the reference kvstore_dist
    contract over the host-side PS in mxnet_tpu/ps.py: pushes of
    locally-reduced gradients run the server-side updater — immediately
    in async mode (worker updates race), or merged across exactly
    ``num_workers`` requests in sync mode; ``set_optimizer`` pickles the
    optimizer to every server shard (reference kvstore.py:231-256); big
    arrays stripe across shards (EncodeKey analog)."""

    def __init__(self, kind, addrs):
        import os

        from .ps import ShardedPSClient

        super().__init__(kind)
        # restarted workers skip startup barriers (reference ps-lite
        # is_recovery, kvstore_dist.h:35-38) — the surviving peers are
        # already past them; their client must REPLAY those rounds as
        # no-ops (no creation-time alignment) until push() resyncs
        from .base import env_flag, env_int

        self._is_recovery = env_flag("MXTPU_IS_RECOVERY", False)
        self._client = ShardedPSClient(addrs.split(","),
                                       align_barriers=not self._is_recovery)
        self._rank = env_int("MXTPU_PROC_ID", 0)
        self._nproc = env_int("MXTPU_NUM_PROCS", 1)
        self._client.hello(self._rank)
        # per-push sync flag (reference sends a server-global kSyncMode
        # command, kvstore.cc:29-38; per-push is strictly safer when two
        # stores share the same servers)
        self._sync = "async" not in kind
        self._meta = {}          # key -> (shape, dtype)
        self._compressor = None  # set_gradient_compression
        # staged pushes: network sends run on the host engine's
        # prioritized lane so the training loop overlaps comm with the
        # rest of backward (reference comm/compute overlap via
        # priority=-key, model.py:87-97); per-key engine vars keep
        # push->pull ordering
        from .engine import FnProperty, get_engine

        self._engine = get_engine()
        self._fnprop = FnProperty.CPU_PRIORITIZED
        self._key_vars = {}
        # clean process exit must send the explicit "bye" (a bare EOF is
        # treated as a crash by the server's dead-node tracking)
        import atexit

        atexit.register(self.close)

    def close(self):
        """Deregister from the servers; idempotent."""
        if getattr(self, "_client", None) is None:
            return
        try:
            self._flush()  # staged sends must land before the bye
        except Exception as e:
            import logging

            # a failed staged send (e.g. the server already died) must
            # not prevent deregistering from the surviving shards
            logging.warning("kvstore close: final flush failed (%s); "
                            "deregistering anyway", e)
        client, self._client = self._client, None
        client.close()

    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._nproc

    def set_gradient_compression(self, compression_params):
        """Gradient compression with error feedback (the later-MXNet
        kvstore capability): ``{"type": "2bit", "threshold": t}`` sends
        packed 2-bit codes (16x smaller wire), ``{"type": "1bit"}``
        sends signs with one adaptive scale (32x); either way the
        quantization error feeds into the next push.  Call BEFORE
        ``init`` — compressed keys must not stripe."""
        from .gradcomp import make_compressor

        if self._meta:
            raise MXNetError(
                "set_gradient_compression must be called before init")
        self._compressor = make_compressor(compression_params)

    def init(self, key, value):
        all_existed = True
        for k, vs in self._normalize(key, value):
            if k in self._meta:
                raise MXNetError(f"key {k!r} already initialized")
            arr = vs[0].asnumpy()
            self._meta[k] = (arr.shape, arr.dtype)
            if self._compressor is not None:
                # compressed pushes are whole-key; the weight must live
                # un-striped on the owner shard
                self._client.mark_unstriped(k)
            if self._rank == 0 or self._is_recovery:
                # recovery inits are non-forcing: they must not clobber
                # trained state on the servers
                existed = self._client.init(k, arr,
                                            force=not self._is_recovery)
                all_existed = all_existed and existed
        if self._is_recovery and not all_existed:
            import logging

            logging.warning(
                "recovery: servers were missing initialized keys — the "
                "previous life crashed before startup completed")
        # Always barrier: rounds the previous life already passed return
        # instantly (generation-numbered on the server), and the first
        # round the peers are still waiting in gets its missing member —
        # both post- and mid-startup crashes recover without deadlock.
        self.barrier()

    def push(self, key, value, priority=0):
        # first push == the training loop has begun: the startup re-join
        # (reference ps-lite is_recovery) is over, so later init /
        # set_optimizer calls get fresh-start semantics again.  Barrier
        # ordinals resync to the servers' released-round counters here:
        # the previous life may have passed mid-training barriers this
        # life never re-executed (periodic checkpoints), and future
        # rounds must pair with the peers' numbering.
        if self._is_recovery:
            self._is_recovery = False
            self._client.resync_barrier()
        for k, vs in self._normalize(key, value):
            if k not in self._meta:
                raise MXNetError(f"key {k!r} not initialized")
            reduced = self._comm.reduce(vs)
            # device reduce synchronizes here; the network send is staged
            # asynchronously at the caller's priority so backward keeps
            # running while earlier grads are in flight
            arr = reduced.asnumpy()
            if self._compressor is not None:
                # 1/2-bit + error feedback; the residual update must
                # happen HERE (in push order), not on the engine thread
                arr = self._compressor.compress(k, arr)
            kvar = self._key_vars.setdefault(k, self._engine.new_variable())
            self._engine.push(
                lambda a=arr, kk=k, c=self._client, s=self._sync:
                    c.push(kk, a, sync=s),
                mutable_vars=(kvar,), prop=self._fnprop, priority=priority)

    def pull(self, key, out=None, priority=0):
        for k, outs in self._normalize(key, out):
            if k not in self._meta:
                raise MXNetError(f"key {k!r} not initialized")
            # honor per-key ordering: a pull observes every push staged
            # before it (reference kvstore_dist.h pull-after-push dep)
            kvar = self._key_vars.get(k)
            if kvar is not None:
                self._engine.wait_for_var(kvar)
                self._engine.check_exceptions()
            shape, dtype = self._meta[k]
            arr = self._client.pull(k, shape, dtype)
            src = NDArray(jnp.asarray(arr), outs[0].context)
            self._comm.broadcast(src, outs)

    def _flush(self):
        """Complete every staged push and surface its errors."""
        for kvar in self._key_vars.values():
            self._engine.wait_for_var(kvar)
        self._engine.check_exceptions()

    def set_optimizer(self, optimizer):
        """Pickle the optimizer to every server shard — the reference's
        server-side-optimizer capability, restored."""
        self._optimizer = optimizer
        if self._rank == 0 or self._is_recovery:
            # A recovering worker (any rank) re-sends the optimizer with
            # if-unset semantics: if the first life crashed before the
            # updater reached the servers, raw-gradient pushes would
            # silently be assigned as weights; if it IS installed, the
            # accumulated momentum/Adam state the surviving workers are
            # training against must not be wiped.
            head = ("set_optimizer_if_unset" if self._is_recovery
                    else "set_optimizer")
            self._client.command(head, pickle.dumps(optimizer))
        self.barrier()

    def save_optimizer_states(self, fname):
        """Optimizer states live on the servers in PS mode — fetch and
        merge them across shards for checkpointing.  Safe to call from
        every rank; only rank 0 writes the file."""
        if self._optimizer is None:
            raise MXNetError("optimizer not initialized")
        if self._rank == 0:
            with open(fname, "wb") as f:
                f.write(pickle.dumps(self._client.get_states()))
        self.barrier()

    def load_optimizer_states(self, fname):
        if self._optimizer is None:
            raise MXNetError("optimizer not initialized")
        if self._rank == 0:
            with open(fname, "rb") as f:
                self._client.set_states(pickle.loads(f.read()))
        self.barrier()

    def dead_nodes(self, timeout=60.0):
        """Ranks whose heartbeat lapsed on every shard (this worker's
        own requests keep refreshing its registration).  The base
        class's ``num_dead_node`` counts this list."""
        return self._client.dead_nodes(timeout)

    def barrier(self):
        self._flush()
        self._client.barrier()

    def send_command_to_servers(self, head, body):
        self._flush()
        self._client.command(head, body)


def create(name="local") -> KVStore:
    """Factory (reference src/kvstore/kvstore.cc:17-45): local /
    local_allreduce_cpu / *device* / dist_sync / dist_async /
    dist_sync_device / dist_async_device.  ``dist_*`` uses the
    parameter-server transport when the launcher provided server shards
    (MXTPU_PS_ADDRS); otherwise collectives-backed sync."""
    import os

    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name.startswith("dist"):
        addrs = os.environ.get("MXTPU_PS_ADDRS")
        if addrs:
            return DistPSKVStore(name, addrs)
        return DistKVStore(name)
    if name in ("local", "local_allreduce_cpu", "local_update_cpu") or "device" in name:
        return KVStore(name)
    raise MXNetError(f"unknown kvstore type {name!r}")
