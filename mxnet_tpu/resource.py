"""Per-context resource manager: temp workspaces and PRNG resources.

Rebuild of the reference ResourceManager (src/resource.cc:96-176,
include/mxnet/resource.h): operators and user code request shared
resources per context instead of allocating their own.  Two kinds,
matching the reference's ``ResourceRequest::Type``:

- ``temp_space``: a scratch buffer shared round-robin over
  ``MXNET_TPU_EXEC_NUM_TEMP`` copies (reference ``MXNET_EXEC_NUM_TEMP``,
  resource.cc:101).  On TPU, XLA owns device scratch; these are *host*
  staging workspaces (pipeline collation, checkpoint IO, custom-op
  scratch), drawn from the native storage pool (src/storage.cc) when
  available.  Each copy owns an engine Var so engine-pushed host work
  can declare a write dependency on the workspace it borrows — the
  reference's per-resource ``engine var`` discipline (resource.cc:179+).
- ``random``: a per-context deterministic PRNG chain (reference
  ``ResourceRandom`` wrapping mshadow::Random, resource.cc:144-176),
  here a JAX key chain forked from the global seed; ``seed()`` reseeds
  every context's chain like ``MXRandomSeed``.
"""

from __future__ import annotations

import os
import threading

import numpy as np

from . import storage
from .context import Context, current_context
from .engine import get_engine

__all__ = ["ResourceRequest", "Resource", "TempSpace", "RandomResource",
           "ResourceManager", "request", "seed"]


class ResourceRequest:
    """What an operator asks for (reference resource.h ResourceRequest)."""

    TEMP_SPACE = "temp_space"
    RANDOM = "random"

    def __init__(self, type):
        if type not in (self.TEMP_SPACE, self.RANDOM):
            raise ValueError(f"unknown resource type {type!r}")
        self.type = type

    def __repr__(self):
        return f"ResourceRequest({self.type!r})"


class Resource:
    """Base resource handle: context + engine var for dependency tracking."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.var = get_engine().new_variable(name=f"resource@{ctx}")


class TempSpace(Resource):
    """A reusable host scratch buffer that grows to the largest request."""

    def __init__(self, ctx: Context):
        super().__init__(ctx)
        self._buf = None
        self._nbytes = 0
        self._retired = []  # outgrown buffers; see get_space
        self._lock = threading.Lock()

    def get_space(self, shape, dtype=np.float32) -> np.ndarray:
        """Borrow a scratch array of ``shape``; contents are undefined.

        A growth reallocation logically invalidates previously borrowed
        arrays, but their backing memory is parked (not returned to the
        pool) until ``release()`` — a still-live view must never alias a
        block the pool has re-issued.  Engine ops that borrow
        concurrently must declare ``self.var`` mutable (the manager's
        round-robin makes collisions rare, as in the reference's
        kTempSpace discipline).
        """
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape)) * dtype.itemsize
        with self._lock:
            if self._buf is None or nbytes > self._nbytes:
                if self._buf is not None:
                    self._retired.append(self._buf)
                self._buf = storage.StagingBuffer((max(nbytes, 1),), np.uint8)
                self._nbytes = nbytes
            flat = self._buf.array[:nbytes]
        return flat.view(dtype)[: int(np.prod(shape))].reshape(shape)

    def release(self):
        """Return backing memory to the pool.  Waits for engine ops that
        declared this workspace's var before freeing, so queued borrows
        finish first; callers must not use previously returned arrays
        afterwards."""
        get_engine().wait_for_var(self.var)
        with self._lock:
            bufs, self._retired = self._retired, []
            if self._buf is not None:
                bufs.append(self._buf)
                self._buf = None
                self._nbytes = 0
        for b in bufs:
            b.close()


class RandomResource(Resource):
    """Per-context deterministic key chain (ResourceRandom analog)."""

    def __init__(self, ctx: Context, seed_state: int):
        super().__init__(ctx)
        self._lock = threading.Lock()
        self.reseed(seed_state)

    def reseed(self, seed_state: int):
        import jax

        # Fold the device id in so each context draws a distinct stream
        # from the same global seed (reference seeds per-device Random
        # with a per-device derived seed, common/utils.h).
        with self._lock:
            self._key = jax.random.fold_in(
                jax.random.PRNGKey(int(seed_state)), self.ctx.device_id)

    def next_key(self):
        import jax

        with self._lock:
            self._key, sub = jax.random.split(self._key)
        return sub


class ResourceManager:
    """Singleton per-process manager (reference ResourceManagerImpl)."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self.num_temp = int(os.environ.get("MXNET_TPU_EXEC_NUM_TEMP", "1"))
        self._temp = {}     # ctx -> [TempSpace] * num_temp
        self._rand = {}     # ctx -> RandomResource
        self._rr = {}       # ctx -> round-robin cursor
        self._seed = 0
        self._lock = threading.Lock()

    @classmethod
    def get(cls) -> "ResourceManager":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
        return cls._instance

    def request(self, ctx: Context, req) -> Resource:
        if isinstance(req, str):
            req = ResourceRequest(req)
        with self._lock:
            if req.type == ResourceRequest.RANDOM:
                if ctx not in self._rand:
                    self._rand[ctx] = RandomResource(ctx, self._seed)
                return self._rand[ctx]
            if ctx not in self._temp:
                self._temp[ctx] = [TempSpace(ctx) for _ in range(self.num_temp)]
                self._rr[ctx] = 0
            i = self._rr[ctx]
            self._rr[ctx] = (i + 1) % self.num_temp
            return self._temp[ctx][i]

    def seed(self, seed_state: int):
        with self._lock:
            self._seed = int(seed_state)
            for r in self._rand.values():
                r.reseed(seed_state)

    def release_all(self):
        """Drop temp buffers back to the pool (memory-pressure hook).

        Snapshot under the lock, release outside it: release() blocks on
        the engine draining workspace borrowers, and a queued engine op
        may itself call request() — waiting while holding the manager
        lock would deadlock the drain."""
        with self._lock:
            spaces = [s for group in self._temp.values() for s in group]
        for s in spaces:
            s.release()
        storage.release_all()


def request(req, ctx: Context | None = None) -> Resource:
    """Module-level convenience: ``mx.resource.request("temp_space")``."""
    return ResourceManager.get().request(ctx or current_context(), req)


def seed(seed_state: int):
    """Reseed every context's random resource (MXRandomSeed analog)."""
    ResourceManager.get().seed(seed_state)
