"""Profiling / tracing facilities.

The reference snapshot has no dedicated profiler (SURVEY.md §5); its
observability surface is Monitor tensor-stat hooks, the Speedometer
callback, `MXNET_ENGINE_INFO` engine traces and check_speed — all of
which exist here (monitor.py, callback.py, test_utils.check_speed).
This module adds the TPU-native tracing layer on top: a thin wrapper
over the JAX/XLA profiler whose traces open in TensorBoard/Perfetto and
show per-op device time on the real chip.

API shape follows the familiar profiler contract:
  profiler.start("/tmp/prof"); ...; profiler.stop()
  with profiler.scope("step"): ...
  profiler.annotate("h2d-copy") decorator
"""

from __future__ import annotations

import contextlib
import functools
import threading

import jax

__all__ = ["start", "stop", "trace", "scope", "annotate", "active_logdir",
           "ProfilerActive", "device_memory", "summarize"]

# The XLA profiler is process-global and start/stop now arrive from two
# threads: the engine step loop (bench/manual captures) and the replica
# HTTP pool (POST /profilez).  All transitions of the active-capture
# state happen under _lock so a concurrent start sees a coherent
# already-active answer instead of racing into the opaque XLA
# double-start crash.
_lock = threading.Lock()
_active_logdir = None    # guarded-by: _lock


class ProfilerActive(RuntimeError):
    """A capture is already running.  Distinguished from plain
    RuntimeError so HTTP surfaces (POST /profilez) can map it to a
    clean 409 instead of a breaker-tripping 500."""


def active_logdir():
    """The logdir of the capture in flight, or None."""
    with _lock:
        return _active_logdir


def start(logdir):
    """Begin capturing an XLA trace into ``logdir`` (TensorBoard
    `profile` plugin / xprof format).

    Raises :class:`ProfilerActive` when a trace is already active — the
    underlying jax failure for a double-start is an opaque XLA error
    that doesn't name the first capture."""
    global _active_logdir
    with _lock:
        if _active_logdir is not None:
            raise ProfilerActive(
                f"a profiler trace is already active (logdir="
                f"{_active_logdir!r}); call profiler.stop() before "
                "starting a new capture")
        jax.profiler.start_trace(logdir)
        _active_logdir = logdir


def stop():
    """Finish the capture started by ``start``.  The active-trace state
    resets even when the underlying ``stop_trace`` raises (a failed
    capture must not wedge every later ``start``)."""
    global _active_logdir
    with _lock:
        try:
            jax.profiler.stop_trace()
        finally:
            _active_logdir = None


@contextlib.contextmanager
def trace(logdir):
    """Capture a trace around a block."""
    start(logdir)
    try:
        yield
    finally:
        stop()


def scope(name, **kwargs):
    """Named region inside an active trace (shows as a span)."""
    return jax.profiler.TraceAnnotation(name, **kwargs)


def annotate(name=None):
    """Decorator: wrap a function in a named trace span."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kw):
            with jax.profiler.TraceAnnotation(label):
                return fn(*args, **kw)

        return wrapper

    return deco


def device_memory(device=None):
    """Live per-buffer device memory stats (storage observability; the
    pooled-allocator stats analog for HBM)."""
    devs = [device] if device is not None else jax.local_devices()
    out = {}
    for d in devs:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        out[str(d)] = stats
    return out


def summarize(logdir, top=20, device_only=True):
    """Aggregate device time per op from the newest trace under
    ``logdir``; returns [(name, total_ms, count)] sorted by time.

    Complements TensorBoard/Perfetto with an in-terminal view — the
    trace itself stays fully compatible with those UIs.
    """
    import glob
    import gzip
    import json
    import os

    candidates = sorted(
        glob.glob(os.path.join(logdir, "plugins", "profile", "*",
                               "*.trace.json.gz")),
        key=os.path.getmtime)
    if not candidates:
        raise FileNotFoundError(f"no trace found under {logdir}; call "
                                "profiler.start/stop first")
    with gzip.open(candidates[-1]) as f:
        events = json.load(f)["traceEvents"]
    device_pids = set()
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = e.get("args", {}).get("name", "")
            if not device_only or "TPU" in pname or "GPU" in pname \
                    or "/device" in pname:
                device_pids.add(e["pid"])
    if device_only and not device_pids:
        import warnings

        warnings.warn("profiler.summarize: no device process in this trace "
                      "(CPU-only capture?); aggregating host events instead",
                      stacklevel=2)
    totals, counts = {}, {}
    for e in events:
        if e.get("ph") == "X" and (not device_pids
                                   or e.get("pid") in device_pids):
            name = e["name"]
            totals[name] = totals.get(name, 0.0) + e.get("dur", 0) / 1e3
            counts[name] = counts.get(name, 0) + 1
    ranked = sorted(totals.items(), key=lambda kv: -kv[1])[:top]
    return [(name, round(ms, 3), counts[name]) for name, ms in ranked]
