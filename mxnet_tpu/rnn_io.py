"""Bucketed sequence data iterators for language modeling.

Rebuild of the reference's bucketing data pipeline
(example/rnn/bucket_io.py: BucketSentenceIter + vocab helpers), the data
side of the bucketing strategy (SURVEY.md §5 "Long-context"): group
variable-length sequences into a small set of padded lengths so each
bucket compiles once (one XLA program per bucket, shared weights via
BucketingModule).
"""

from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter", "build_vocab", "encode_sentences"]


def build_vocab(sentences, start_label=1, invalid_label=0):
    """token -> id map over tokenized sentences (bucket_io
    default_build_vocab); id 0 is reserved for padding/invalid."""
    vocab = {}
    nxt = start_label
    for sent in sentences:
        for tok in sent:
            if tok not in vocab:
                vocab[tok] = nxt
                nxt += 1
    return vocab


def encode_sentences(sentences, vocab):
    return [[vocab[tok] for tok in sent] for sent in sentences]


class BucketSentenceIter(DataIter):
    """Bucketed, padded sentence iterator (bucket_io.BucketSentenceIter).

    Parameters
    ----------
    sentences : list of list of int
        Encoded sentences (see ``encode_sentences``).
    batch_size : int
    buckets : list of int, optional
        Bucket lengths; default = auto from the length histogram
        (lengths that hold >= 1 batch, like the reference's
        default_gen_buckets).
    invalid_label : int
        Padding id (default 0).
    data_name, label_name : str
        Labels are the input shifted one step left (next-token target),
        the reference LM convention.
    init_states : list of (name, shape), optional
        Extra zero-filled state inputs appended to provide_data
        (explicit-unroll LSTM state feeds, bucket_io usage in
        example/rnn/lstm_bucketing.py).
    """

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=0,
                 data_name="data", label_name="softmax_label",
                 init_states=None, shuffle=True, seed=0):
        super().__init__()
        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.invalid_label = invalid_label
        self.init_states = list(init_states or [])
        self._rng = np.random.RandomState(seed)
        self._shuffle = shuffle

        lengths = [len(s) for s in sentences if len(s) > 0]
        if not lengths:
            raise MXNetError("no non-empty sentences")
        if buckets is None:
            hist = np.bincount(lengths)
            buckets = [i for i, n in enumerate(np.cumsum(hist[::-1])[::-1])
                       if i > 0 and n >= batch_size and hist[i] > 0]
            if not buckets:
                buckets = [max(lengths)]
        self.buckets = sorted(buckets)

        self._data = [[] for _ in self.buckets]
        n_dropped = 0
        for sent in sentences:
            if not sent:
                continue
            for i, bkt in enumerate(self.buckets):
                if len(sent) <= bkt:
                    row = np.full(bkt, invalid_label, np.int32)
                    row[:len(sent)] = sent
                    self._data[i].append(row)
                    break
            else:
                n_dropped += 1
        if n_dropped:
            import logging

            logging.warning("BucketSentenceIter: dropped %d sentences longer "
                            "than the largest bucket (%d)", n_dropped,
                            self.buckets[-1])
        self._data = [np.asarray(rows, np.int32) if rows else
                      np.zeros((0, bkt), np.int32)
                      for rows, bkt in zip(self._data, self.buckets)]
        self.default_bucket_key = self.buckets[-1]
        self.reset()

    @property
    def provide_data(self):
        descs = [DataDesc(self.data_name,
                          (self.batch_size, self.default_bucket_key))]
        descs += [DataDesc(n, s) for n, s in self.init_states]
        return descs

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size, self.default_bucket_key))]

    def reset(self):
        self._plan = []
        for i, rows in enumerate(self._data):
            idx = np.arange(len(rows))
            if self._shuffle:
                self._rng.shuffle(idx)
            for start in range(0, len(rows) - self.batch_size + 1,
                              self.batch_size):
                self._plan.append((i, idx[start:start + self.batch_size]))
        if self._shuffle:
            self._rng.shuffle(self._plan)
        self._cursor = 0

    def next(self) -> DataBatch:
        if self._cursor >= len(self._plan):
            raise StopIteration
        bkt_i, idx = self._plan[self._cursor]
        self._cursor += 1
        bkt = self.buckets[bkt_i]
        data = self._data[bkt_i][idx]
        # next-token labels: shift left, pad tail with invalid_label
        label = np.full_like(data, self.invalid_label)
        label[:, :-1] = data[:, 1:]
        provide_data = [DataDesc(self.data_name, data.shape)]
        batch_data = [nd.array(data)]
        for name, shape in self.init_states:
            provide_data.append(DataDesc(name, shape))
            batch_data.append(nd.zeros(shape))
        return DataBatch(
            batch_data, [nd.array(label)],
            bucket_key=bkt,
            provide_data=provide_data,
            provide_label=[DataDesc(self.label_name, label.shape)])
