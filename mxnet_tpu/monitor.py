"""Tensor-stat monitor (rebuild of python/mxnet/monitor.py).

Installs a per-output callback on executors (the reference wires this via
MXExecutorSetMonitorCallback; here the executor switches to un-fused
eager evaluation while a monitor is installed, the analog of bulk-exec
being disabled under monitoring, graph_executor.cc:904)."""

from __future__ import annotations

import logging
import re

from .ndarray import NDArray

__all__ = ["Monitor", "ServeMonitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return abs(x).asnumpy().mean()

        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))

        self.stat_helper = stat_helper

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, array in zip(exe.arg_names, exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
            for name, array in zip(exe.arg_names, exe.grad_arrays):
                if array is not None and self.re_prog.match(name):
                    self.queue.append((self.step, name + "_grad",
                                       self.stat_func(array)))
        res = sorted(self.queue, key=lambda x: x[1]) if self.sort else self.queue
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v_list in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, str(v_list))


class ServeMonitor:
    """Periodic logger for the serving engine, the inference-side
    analog of ``callback.Speedometer``'s samples/sec line and this
    module's tic/toc convention: call :meth:`tic` once per engine
    step; every ``interval`` steps it snapshots
    ``serve.Engine.stats()`` and logs one line.

        mon = mx.monitor.ServeMonitor(engine, interval=100)
        while engine.scheduler.has_work():
            engine.step()
            mon.tic()
    """

    def __init__(self, engine, interval=100, logger=None):
        self.engine = engine
        self.interval = int(interval)
        if self.interval < 1:
            raise ValueError(
                f"interval must be >= 1 (got {interval})")
        self.step = 0
        self.logger = logger or logging.getLogger(__name__)

    def tic(self):
        self.step += 1
        if self.step % self.interval == 0:
            self.log_now()

    @staticmethod
    def _fmt(value):
        """Grep/parse-stable field: ``-`` for not-yet-measured (None),
        one decimal otherwise (raw floats would make the line width and
        precision vary run to run)."""
        return "-" if value is None else f"{float(value):.1f}"

    @staticmethod
    def _fmt_reasons(reasons):
        """Cumulative rejection reasons as a grep-stable bracket:
        ``[deadline=2,queue_full=1]`` sorted by reason, ``[-]`` when
        none — back-pressure and its cause are visible straight from
        the log line, no metrics endpoint needed."""
        if not reasons:
            return "-"
        return ",".join(f"{k}={reasons[k]}" for k in sorted(reasons))

    def log_now(self):
        # the periodic logging cadence doubles as the local time-series
        # sampling beat: with MXTPU_TIMESERIES set, each log tick also
        # snapshots the metrics registry into the bounded ring (rate-
        # limited per MXTPU_TIMESERIES_INTERVAL), so windowed rates —
        # tok/s over the last minute, reject rate over five — are
        # readable from /statusz without any external scraper.  A
        # no-op (None check) when the ring is unconfigured.
        from .telemetry import timeseries

        timeseries.sample()
        s = self.engine.stats()
        rate = (s.decode_tok_per_sec if s.decode_tok_per_sec is not None
                else s.total_tok_per_sec)
        # the tok/s above is fed from ACTUAL per-iteration emitted
        # counts, so it stays honest with speculative decoding on; the
        # spec tail (acceptance rate / mean accepted-per-verify) only
        # appears once a verify has run — plain-decode lines are
        # byte-identical to the pre-spec format
        spec = ""
        if getattr(s, "spec_verifies", 0):
            spec = (f" spec={s.spec_accept_rate:.2f}"
                    f"/{s.accepted_per_verify:.2f}")
        # performance-attribution tail (the same only-once-measured
        # rule as the spec tail): appears only after a sampled timing
        # exists (MXTPU_PERF_ATTRIB_SAMPLE>0 and a sampled step ran),
        # so plain lines stay byte-identical to the pre-attribution
        # format — and engines without perf_summary (fakes, older
        # duck-typed drivers) log exactly as before
        perf = ""
        summary = getattr(self.engine, "perf_summary", None)
        p = summary() if callable(summary) else None
        if p and p.get("sampled"):
            mfu = p.get("mfu")
            mfu_s = "-" if mfu is None else f"{100.0 * mfu:.1f}%"
            tf = p.get("tok_flops")
            tf_s = "-" if tf is None else f"{tf / 1e6:.2f}M"
            perf = f" mfu={mfu_s} tok_flops={tf_s}"
        self.logger.info(
            "Serve: step %7d queue=%d running=%d done=%d rej=%d[%s] "
            "preempt=%d blocks=%d/%d (%.0f%%) ttft_ms=%s tok/s=%s%s%s",
            s.steps, s.queue_depth, s.running, s.completed, s.rejected,
            self._fmt_reasons(getattr(s, "reject_reasons", None)),
            s.preemptions, s.blocks_in_use, s.blocks_total,
            100.0 * s.block_utilization, self._fmt(s.ttft_ms_mean),
            self._fmt(rate), spec, perf)
        return s
