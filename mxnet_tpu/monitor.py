"""Tensor-stat monitor (rebuild of python/mxnet/monitor.py).

Installs a per-output callback on executors (the reference wires this via
MXExecutorSetMonitorCallback; here the executor switches to un-fused
eager evaluation while a monitor is installed, the analog of bulk-exec
being disabled under monitoring, graph_executor.cc:904)."""

from __future__ import annotations

import logging
import re

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return abs(x).asnumpy().mean()

        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))

        self.stat_helper = stat_helper

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        for exe in self.exes:
            for name, array in zip(exe.arg_names, exe.arg_arrays):
                if self.re_prog.match(name):
                    self.queue.append((self.step, name, self.stat_func(array)))
            for name, array in zip(exe.arg_names, exe.grad_arrays):
                if array is not None and self.re_prog.match(name):
                    self.queue.append((self.step, name + "_grad",
                                       self.stat_func(array)))
        res = sorted(self.queue, key=lambda x: x[1]) if self.sort else self.queue
        self.queue = []
        return res

    def toc_print(self):
        for n, k, v_list in self.toc():
            logging.info("Batch: %7d %30s %s", n, k, str(v_list))
