"""Generic named registries.

Rebuild of dmlc-core's registry facility (used by the reference for
operators, NDArray functions, data iterators, optimizers and kvstores —
e.g. src/operator/operator.cc:11-22).  Registries are what make the op
surface *runtime-discoverable*: the Python NDArray/Symbol modules generate
their functions by enumerating a registry, exactly as the reference's
frontends enumerate ``MXSymbolListAtomicSymbolCreators``.
"""

from __future__ import annotations

__all__ = ["Registry"]


class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._entries: dict = {}

    def register(self, name=None, entry=None, aliases=()):
        """Register an entry, usable directly or as a decorator."""

        def _do(entry, name=name):
            key = name if name is not None else getattr(entry, "__name__", None)
            if key is None:
                raise ValueError(f"{self.kind} registry: cannot infer name")
            lname = key.lower()
            if lname in self._entries and self._entries[lname] is not entry:
                raise ValueError(f"{self.kind} registry: duplicate entry {key!r}")
            self._entries[lname] = entry
            for alias in aliases:
                self._entries[alias.lower()] = entry
            return entry

        if entry is not None:
            return _do(entry)
        if callable(name) and not isinstance(name, str):
            entry, name = name, None
            return _do(entry, None)
        return _do

    def get(self, name: str):
        key = name.lower()
        if key not in self._entries:
            raise KeyError(
                f"unknown {self.kind} {name!r}; registered: {sorted(set(self._entries))}"
            )
        return self._entries[key]

    def remove(self, name: str):
        """Drop an entry (used to evict transient process-local ops)."""
        self._entries.pop(name.lower(), None)

    def find(self, name: str):
        return self._entries.get(name.lower())

    def __contains__(self, name):
        return name.lower() in self._entries

    def list(self):
        return sorted(self._entries)

    def items(self):
        return self._entries.items()
