"""ShardedTrainer: a Symbol's full training step compiled over a mesh.

This is the TPU-native path the reference cannot express: instead of
per-device executors + KVStore push/pull (§3.3/§3.4), the *entire* train
step — forward, backward, gradient all-reduce, optimizer update — is one
jitted XLA program whose inputs carry ``NamedSharding``s.  The GSPMD
partitioner inserts the collectives: batch sharded over ``dp`` yields a
gradient psum over ICI (the dist_sync path collapsed into the step,
SURVEY.md §3.4 "TPU translation"); parameters sharded over ``tp`` yield
tensor-parallel matmul collectives; sequence-sharded activations over
``sp`` yield context parallelism.

The Module/KVStore stack remains the MXNet-compatible surface; this
trainer is the performance path for pod-scale runs.
"""

from __future__ import annotations

import logging
import os
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .. import random as _random
from .. import telemetry
from ..base import MXNetError, np_dtype
from ..executor import _CompiledGraph
from ..initializer import Uniform
from ..lint.annotations import hot_path
from .. import ndarray as nd

__all__ = ["ShardedTrainer", "sgd_opt", "adam_opt", "adamw_opt",
           "cached_sgd_step"]


def cached_sgd_step(cache, loss_fn, make_objective, has_aux=False):
    """Shared jitted-SGD-step cache for the module wrappers
    (PipelineModule / MoELayer).

    Returns a jitted ``step(params, x, lr, *extra) -> (loss, aux,
    new_params)`` (``aux`` is None unless ``has_aux``) cached per
    ``loss_fn`` OBJECT — never per ``id(loss_fn)``: an id can be
    recycled after GC, handing a brand-new loss_fn another function's
    compiled program (mxtpu-lint's jit-cache-capture rule).  Keying by
    the object keeps the entry correct, and the bounded eviction below
    keeps fresh-lambda call sites from pinning compiled programs (and
    the objective closures they capture) forever.  Callers must still
    pass a stable function object or every call recompiles.

    ``params`` is donated (TPU-only, like every train step in this
    repo): the update reuses the weight buffers in place, so callers
    must rebind — ``…, self.params = step(self.params, …)`` — and never
    read the donated pytree afterwards.  Cross-module analysis cannot
    see this factory's jit, so call sites annotate the binding with
    ``# mxtpu-lint: donates=0`` to put the use-after-donate checker on
    duty there.  ``make_objective(loss_fn, x,
    *extra)`` builds the ``params -> loss`` (or ``params -> (loss,
    aux)`` with ``has_aux``) objective at trace time.
    """
    from ..optimizer import _donate

    key = (loss_fn, has_aux)
    step = cache.get(key)
    if step is None:
        def step_fn(params, x, lr, *extra):
            objective = make_objective(loss_fn, x, *extra)
            if has_aux:
                (loss, aux), grads = jax.value_and_grad(
                    objective, has_aux=True)(params)
            else:
                loss, grads = jax.value_and_grad(objective)(params)
                aux = None
            new_params = jax.tree_util.tree_map(lambda p, g: p - lr * g,
                                                params, grads)
            return loss, aux, new_params

        step = jax.jit(step_fn, donate_argnums=_donate(0))
        # bounded like pipeline's _RUN_CACHE: evict oldest first
        while len(cache) >= 64:
            cache.pop(next(iter(cache)))
        cache[key] = step
    return step


def _clip_grads(grads, clip_gradient=None, clip_by_global_norm=None):
    """Gradient clipping shared by the optimizer factories.

    ``clip_gradient`` is the reference's per-element clamp to
    [-c, c] (optimizer.py SGD/Adam ``clip_gradient``); modern
    ``clip_by_global_norm`` rescales the whole pytree when its L2 norm
    exceeds the bound.  Both compute in f32; under a sharded step the
    global-norm sum becomes one scalar psum inserted by the
    partitioner."""
    if clip_gradient is not None:
        c = float(clip_gradient)
        grads = {k: jnp.clip(g.astype(jnp.float32), -c, c)
                 for k, g in grads.items()}
    if clip_by_global_norm is not None:
        c = float(clip_by_global_norm)
        sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                 for g in grads.values())
        norm = jnp.sqrt(sq)
        scale = jnp.minimum(1.0, c / jnp.maximum(norm, 1e-12))
        grads = {k: g.astype(jnp.float32) * scale for k, g in grads.items()}
    return grads


def sgd_opt(learning_rate=0.01, momentum=0.9, weight_decay=0.0,
            clip_gradient=None, clip_by_global_norm=None,
            state_dtype=None):
    """Functional SGD(+momentum) over a param pytree.

    ``state_dtype`` sets the dtype the momentum buffer is STORED in
    (compute is always f32).  Default: the param dtype — with bf16
    params that halves optimizer-state HBM traffic per step; pass
    ``float32`` for full-precision accumulation (the MLPerf-style
    recipe when params themselves are bf16)."""
    sdt = jnp.dtype(state_dtype) if state_dtype is not None else None

    def init(params):
        if momentum == 0.0:
            return {}
        return {k: jnp.zeros_like(v, dtype=sdt or v.dtype)
                for k, v in params.items()}

    def update(grads, state, params, lr_scale=1.0):
        grads = _clip_grads(grads, clip_gradient, clip_by_global_norm)
        lr = learning_rate * lr_scale
        new_params, new_state = {}, {}
        for k, p in params.items():
            g = grads[k].astype(jnp.float32) + weight_decay * p.astype(jnp.float32)
            if momentum != 0.0:
                m = momentum * state[k].astype(jnp.float32) - lr * g
                new_state[k] = m.astype(sdt or p.dtype)
                new_params[k] = (p.astype(jnp.float32) + m).astype(p.dtype)
            else:
                new_params[k] = (p.astype(jnp.float32) - lr * g).astype(p.dtype)
        return new_params, new_state

    return init, update


def adam_opt(learning_rate=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
             weight_decay=0.0, decoupled=False,
             clip_gradient=None, clip_by_global_norm=None):
    """Functional Adam over a param pytree.

    ``decoupled=True`` gives AdamW: weight decay multiplies the weights
    by (1 - lr*wd) instead of being folded into the gradient."""

    def init(params):
        z = {k: jnp.zeros_like(v, dtype=jnp.float32) for k, v in params.items()}
        return {"m": z, "v": {k: jnp.zeros_like(val, dtype=jnp.float32)
                              for k, val in params.items()},
                "t": jnp.zeros((), jnp.int32)}

    def update(grads, state, params, lr_scale=1.0):
        grads = _clip_grads(grads, clip_gradient, clip_by_global_norm)
        t = state["t"] + 1
        lr_t = (learning_rate * lr_scale
                * jnp.sqrt(1 - beta2**t.astype(jnp.float32))
                / (1 - beta1**t.astype(jnp.float32)))
        new_params, new_m, new_v = {}, {}, {}
        for k, p in params.items():
            pf = p.astype(jnp.float32)
            g = grads[k].astype(jnp.float32)
            if not decoupled:
                g = g + weight_decay * pf
            m = beta1 * state["m"][k] + (1 - beta1) * g
            v = beta2 * state["v"][k] + (1 - beta2) * jnp.square(g)
            new_m[k], new_v[k] = m, v
            if decoupled:
                # decay strength follows the SCHEDULED lr (standard AdamW)
                pf = pf * (1.0 - learning_rate * lr_scale * weight_decay)
            new_params[k] = (pf - lr_t * m
                             / (jnp.sqrt(v) + eps)).astype(p.dtype)
        return new_params, {"m": new_m, "v": new_v, "t": t}

    return init, update


def adamw_opt(learning_rate=1e-3, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.0, clip_gradient=None,
              clip_by_global_norm=None):
    """Functional AdamW: adam_opt with decoupled weight decay."""
    return adam_opt(learning_rate, beta1, beta2, eps, weight_decay,
                    decoupled=True, clip_gradient=clip_gradient,
                    clip_by_global_norm=clip_by_global_norm)


def lars_opt(learning_rate=0.01, momentum=0.9, weight_decay=0.0,
             trust_coefficient=0.001, eps=1e-9,
             clip_gradient=None, clip_by_global_norm=None):
    """Functional LARS (You et al. 2017) — SGD+momentum with a
    per-layer trust ratio ``eta*||w||/(||g||+wd*||w||)``, the standard
    large-batch ResNet optimizer on TPU pods.  Bias/norm params
    (ndim <= 1) update as plain SGD (standard exclusion)."""

    def init(params):
        return {k: jnp.zeros_like(v, dtype=jnp.float32)
                for k, v in params.items()}

    def update(grads, state, params, lr_scale=1.0):
        grads = _clip_grads(grads, clip_gradient, clip_by_global_norm)
        lr = learning_rate * lr_scale
        new_params, new_state = {}, {}
        for k, p in params.items():
            pf = p.astype(jnp.float32)
            g = grads[k].astype(jnp.float32)
            if p.ndim > 1:
                w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
                g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
                ratio = jnp.where(
                    (w_norm > 0) & (g_norm > 0),
                    trust_coefficient * w_norm
                    / (g_norm + weight_decay * w_norm + eps), 1.0)
            else:
                ratio = 1.0
            g = g + weight_decay * pf
            m = momentum * state[k] + lr * ratio * g
            new_state[k] = m
            new_params[k] = (pf - m).astype(p.dtype)
        return new_params, new_state

    return init, update


def lamb_opt(learning_rate=1e-3, beta1=0.9, beta2=0.999, eps=1e-6,
             weight_decay=0.0, clip_gradient=None,
             clip_by_global_norm=None):
    """Functional LAMB (You et al. 2019) — Adam moments with a
    per-layer ``||w||/||r||`` rescale of the update direction, the
    large-batch BERT/transformer optimizer.  Bias/norm params skip the
    adaptation."""

    def init(params):
        z = {k: jnp.zeros_like(v, dtype=jnp.float32)
             for k, v in params.items()}
        return {"m": z, "v": {k: jnp.zeros_like(val, dtype=jnp.float32)
                              for k, val in params.items()},
                "t": jnp.zeros((), jnp.int32)}

    def update(grads, state, params, lr_scale=1.0):
        grads = _clip_grads(grads, clip_gradient, clip_by_global_norm)
        t = state["t"] + 1
        tf = t.astype(jnp.float32)
        lr = learning_rate * lr_scale
        new_params, new_m, new_v = {}, {}, {}
        for k, p in params.items():
            pf = p.astype(jnp.float32)
            g = grads[k].astype(jnp.float32)
            m = beta1 * state["m"][k] + (1 - beta1) * g
            v = beta2 * state["v"][k] + (1 - beta2) * jnp.square(g)
            new_m[k], new_v[k] = m, v
            m_hat = m / (1 - beta1**tf)
            v_hat = v / (1 - beta2**tf)
            r = m_hat / (jnp.sqrt(v_hat) + eps) + weight_decay * pf
            if p.ndim > 1:
                w_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
                r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
                ratio = jnp.where((w_norm > 0) & (r_norm > 0),
                                  w_norm / r_norm, 1.0)
            else:
                ratio = 1.0
            new_params[k] = (pf - lr * ratio * r).astype(p.dtype)
        return new_params, {"m": new_m, "v": new_v, "t": t}

    return init, update


_OPTS = {"sgd": sgd_opt, "adam": adam_opt, "adamw": adamw_opt,
         "lars": lars_opt, "lamb": lamb_opt}


class ShardedTrainer:
    """Compile and run a full sharded train step for a Symbol.

    Parameters
    ----------
    symbol : Symbol with a loss head (SoftmaxOutput / MakeLoss / ...)
    input_shapes : dict name -> global shape (batch dim = global batch)
    mesh : jax.sharding.Mesh; axes referenced by batch_axis/param_specs
    batch_axis : mesh axis name data is sharded over (data parallelism)
    param_specs : {param_name_or_regex: PartitionSpec} for tensor/expert
        parallel parameter sharding; unlisted params are replicated
    sequence_specs : {input_name: PartitionSpec} extra input shardings
        (e.g. sequence axis over 'sp' for context parallelism)
    optimizer : 'sgd' | 'adam' | 'adamw' | (init_fn, update_fn)
    dtype : compute dtype for params (bfloat16 recommended on TPU)
    grad_accum_steps : process the global batch as N sequential
        microbatches inside one compiled step (single optimizer update).
        Exact for deterministic graphs; dropout draws per-microbatch RNG
        and BatchNorm sees microbatch statistics (standard caveat)
    shard_optimizer_state : ZeRO-1 — momentum/Adam moments of
        replicated params shard over the data axis, cutting optimizer
        memory by the dp degree; math is unchanged (XLA gathers shards
        where the update needs them)
    fsdp : ZeRO-3 — STORE parameters sharded over the data axis
        (largest dp-divisible dim per param).  XLA all-gathers each
        param where a layer consumes it and reduce-scatters its
        gradient, so per-device param+grad+optimizer memory drops by
        the dp degree while the math is unchanged.  Composes with
        param_specs (explicit specs win, e.g. tensor-parallel layers)
        and grad_accum_steps.  ``fsdp_min_size`` (elements) keeps small
        params replicated — their all-gather latency outweighs the
        bytes saved
    lr_scheduler : ``mx.lr_scheduler.LRScheduler`` (or any
        ``step -> lr`` callable) evaluated on host each step; the value
        enters the compiled step as a traced scalar, so schedules
        (warmup, factor decay, cosine) never trigger recompilation
    """

    def __init__(self, symbol, input_shapes, mesh=None, batch_axis="dp",
                 param_specs=None, sequence_specs=None, optimizer="sgd",
                 optimizer_params=None, initializer=None, dtype="float32",
                 input_dtypes=None, rescale_grad=None, grad_accum_steps=1,
                 shard_optimizer_state=False, lr_scheduler=None,
                 fsdp=False, fsdp_min_size=2 ** 17, seq_axis=None):
        if mesh is None:
            from .mesh import local_mesh

            mesh = local_mesh(batch_axis)
        self.mesh = mesh
        self.batch_axis = batch_axis
        self.symbol = symbol
        self._graph = _CompiledGraph(symbol)
        self.input_names = list(input_shapes)
        self.param_names = [n for n in symbol.list_arguments()
                            if n not in input_shapes]
        self.aux_names = symbol.list_auxiliary_states()
        self._dtype = np_dtype(dtype)

        arg_shapes, out_shapes, aux_shapes = symbol.infer_shape(**input_shapes)
        arg_types, _, _ = symbol.infer_type(
            **{k: v for k, v in (input_dtypes or {}).items()})
        name2shape = dict(zip(symbol.list_arguments(), arg_shapes))
        name2type = dict(zip(symbol.list_arguments(), arg_types))
        self.out_shapes = out_shapes
        self._input_shapes = dict(input_shapes)
        self._input_dtypes = {k: name2type.get(k) or np.float32
                              for k in self.input_names}
        if input_dtypes:
            self._input_dtypes.update(input_dtypes)
        # mixed precision: float data inputs follow the compute dtype;
        # labels stay f32 (bf16 cannot represent class ids > 256 exactly)
        for k in self.input_names:
            if (self._dtype != np.float32
                    and np.issubdtype(self._input_dtypes[k], np.floating)
                    and not k.endswith("label")):
                self._input_dtypes[k] = self._dtype

        # -- initialize params on host, then place with shardings ----------
        initializer = initializer or Uniform(0.07)

        fsdp_dp = mesh.shape.get(batch_axis, 1) if fsdp else 1

        def fsdp_spec(name, shape):
            """FSDP / ZeRO-3: STORE the param sharded over the data axis
            (largest dp-divisible dim); XLA all-gathers it where a layer
            consumes it and reduce-scatters its gradient — per-device
            param+grad+state memory drops by the dp degree.  Small
            params (< fsdp_min_size elements) stay replicated: their
            all-gather latency outweighs the bytes saved."""
            size = int(np.prod(shape)) if shape else 0
            if fsdp_dp <= 1 or size < fsdp_min_size:
                return PartitionSpec()
            dims = [d for d in range(len(shape)) if shape[d] % fsdp_dp == 0]
            if not dims:
                return PartitionSpec()
            dim = max(dims, key=lambda d: shape[d])
            spec = [None] * len(shape)
            spec[dim] = batch_axis
            return PartitionSpec(*spec)

        # param_specs resolve through the shared regex-rule partitioner
        # (parallel/partition.py — the same matcher serve.Engine shards
        # with); dict order is rule priority, mode="full" keeps the
        # historical exact-name-or-fullmatch key contract, and the FSDP
        # heuristic remains the fallback for unmatched params
        from .partition import match_partition_rules

        param_spec_tree = match_partition_rules(
            (param_specs or {}).items(),
            {n: name2shape[n] for n in self.param_names},
            default=fsdp_spec, mode="full")
        self.param_shardings = {n: NamedSharding(mesh, param_spec_tree[n])
                                for n in self.param_names}
        self._replicated = NamedSharding(mesh, PartitionSpec())

        params = {}
        for name in self.param_names:
            host = nd.zeros(name2shape[name], dtype=np.float32)
            initializer(name, host)
            params[name] = jax.device_put(
                host.asnumpy().astype(self._dtype), self.param_shardings[name])
        self.params = params
        aux = {}
        for name, shp in zip(self.aux_names, aux_shapes):
            host = nd.zeros(shp, dtype=np.float32)
            initializer(name, host)
            aux[name] = jax.device_put(host.asnumpy(), self._replicated)
        self.aux = aux

        # -- optimizer ------------------------------------------------------
        import inspect

        if isinstance(optimizer, str):
            opt_factory = _OPTS[optimizer]
            init_fn, update_fn = opt_factory(**(optimizer_params or {}))
            # the scale denominator must be the optimizer's REAL base lr,
            # including each factory's own default (sgd 0.01, adam 1e-3)
            factory_default = inspect.signature(
                opt_factory).parameters["learning_rate"].default
            base_lr = float((optimizer_params or {}).get(
                "learning_rate", factory_default))
        else:
            init_fn, update_fn = optimizer
            base_lr = float((optimizer_params or {}).get(
                "learning_rate", 1.0))
            try:
                sig = inspect.signature(update_fn)
            except (TypeError, ValueError):
                sig = None  # non-introspectable (C extension etc.)
            if sig is not None:
                # the schedule hook must actually be NAMED lr_scale (or
                # absorbed by **kwargs) — a probe that only checks arity
                # would feed the traced multiplier into an unrelated
                # parameter (clip etc.); the call site passes it by
                # keyword for the same reason
                try:
                    sig.bind(None, None, None, lr_scale=1.0)
                    has_scale = True
                except TypeError:
                    has_scale = False
            else:
                has_scale = lr_scheduler is not None
            if not has_scale:
                # custom optimizers predating lr scaling cannot honor a
                # schedule — refuse rather than silently train flat
                if lr_scheduler is not None:
                    raise MXNetError(
                        "lr_scheduler requires the custom optimizer's "
                        "update(grads, state, params, lr_scale) to accept "
                        "an 'lr_scale' argument") from None
                _inner_update = update_fn
                try:  # 4-positional-arg legacy form: feed a constant 1.0
                    sig.bind(None, None, None, 1.0)
                    update_fn = (lambda grads, state, params, lr_scale=1.0:
                                 _inner_update(grads, state, params, 1.0))
                except TypeError:
                    update_fn = (lambda grads, state, params, lr_scale=1.0:
                                 _inner_update(grads, state, params))
        self._lr_scheduler = lr_scheduler
        if lr_scheduler is not None and hasattr(lr_scheduler, "base_lr"):
            # the reference optimizer wiring (optimizer.py:43-45): the
            # scheduler's base lr IS the optimizer's lr
            lr_scheduler.base_lr = base_lr
        self._base_lr = base_lr
        self._num_update = 0
        # param-shaped state (momentum etc.) inherits the param shardings
        # through zeros_like; scalar/odd-shaped leaves (Adam's step count)
        # must be pinned to the mesh explicitly or multi-device jit sees
        # mixed device sets
        # ZeRO-1: momentum/Adam moments of REPLICATED params shard over
        # the data axis (each dp rank owns 1/dp of the state; XLA
        # inserts the gather when the update combines sharded state with
        # replicated params) — optimizer memory drops by the dp degree
        dp_size = mesh.shape.get(batch_axis, 1)
        # built lazily: meshes without a batch axis (pure tp/sp setups)
        # must not fail NamedSharding validation when ZeRO is off
        zero_sharding = (NamedSharding(mesh, PartitionSpec(batch_axis))
                         if shard_optimizer_state
                         and batch_axis in mesh.shape else None)

        def _place_state(leaf):
            sh = getattr(leaf, "sharding", None)
            param_sharded = (isinstance(sh, NamedSharding)
                             and sh.mesh == mesh
                             and sh.spec != PartitionSpec())
            if param_sharded:
                return leaf  # tensor-parallel state follows its param
            if (zero_sharding is not None
                    and getattr(leaf, "ndim", 0) >= 1
                    and leaf.shape[0] % dp_size == 0):
                return jax.device_put(leaf, zero_sharding)
            if isinstance(sh, NamedSharding) and sh.mesh == mesh:
                return leaf
            return jax.device_put(leaf, self._replicated)

        self.opt_state = jax.tree_util.tree_map(_place_state, init_fn(params))
        self._update_fn = update_fn

        # Loss-layer backward is un-normalized (reference SoftmaxOutput
        # contract); like Module.init_optimizer, default rescale to
        # 1/global_batch.
        if rescale_grad is None:
            rescale_grad = 1.0 / next(iter(input_shapes.values()))[0]
        self._rescale_grad = rescale_grad
        # gradient accumulation: the global batch is processed as
        # grad_accum_steps sequential microbatches inside ONE compiled
        # step (lax.scan), with a single optimizer update — activation
        # memory scales with the microbatch, so models whose activations
        # exceed HBM at the full batch still train
        self._accum = int(grad_accum_steps)
        if self._accum > 1:
            for name, shp in input_shapes.items():
                if shp[0] % self._accum:
                    raise ValueError(
                        f"batch dim of {name!r} ({shp[0]}) must be "
                        f"divisible by grad_accum_steps ({self._accum})")

        self.batch_shardings = {
            n: NamedSharding(mesh, (sequence_specs or {}).get(
                n, PartitionSpec(batch_axis)))
            for n in self.input_names}
        # the sequence-parallel mesh axis: FlashAttention ops in the
        # graph route to ring attention over it — per-shard local
        # attention over a sharded sequence would be silently wrong.
        # Explicit ``seq_axis=`` wins; otherwise inferred as the one
        # non-batch axis sequence_specs shard over, and AMBIGUOUS specs
        # raise rather than silently disabling the routing (which would
        # make GSPMD all-gather the sequence at every attention).
        if seq_axis is not None:
            self._attn_seq_axis = seq_axis
        else:
            seq_axes = set()
            for spec in (sequence_specs or {}).values():
                for entry in spec:
                    for nm in (entry if isinstance(entry, (tuple, list))
                               else (entry,)):
                        if nm is not None and nm != batch_axis:
                            seq_axes.add(nm)
            if len(seq_axes) > 1:
                raise ValueError(
                    f"sequence_specs shard over multiple non-batch axes "
                    f"{sorted(seq_axes)}; pass seq_axis= to name the "
                    "sequence-parallel axis explicitly")
            self._attn_seq_axis = seq_axes.pop() if seq_axes else None
        self._key = _random.next_key()
        # telemetry handles (no-op objects when disabled).  step time is
        # HOST time around the jitted call — dispatch cost when XLA runs
        # async, the full device step when the result is consumed
        self._tel_steps = telemetry.counter(
            "mxtpu_trainer_steps_total", "ShardedTrainer optimizer steps")
        self._tel_step_secs = telemetry.histogram(
            "mxtpu_trainer_step_seconds",
            "host wall time per train_step dispatch")
        self._tel_data_wait = telemetry.histogram(
            "mxtpu_trainer_data_wait_seconds",
            "fit() wait on the host->device staging queue")
        self._build_steps()

    # ------------------------------------------------------------------ #
    def _build_steps(self):
        from ..ops.attention import spmd_attention

        graph = self._graph

        n_accum = self._accum
        mesh, batch_axis = self.mesh, self.batch_axis
        seq_axis = self._attn_seq_axis

        def grads_of(params, aux, batch, sub):
            def f(p):
                # ambient mesh for fused-attention ops: their Mosaic
                # kernels must shard_map over the batch axis inside a
                # multi-device program (GSPMD can't partition them), and
                # a sharded sequence axis routes them to ring attention
                with spmd_attention(mesh, batch_axis, seq_axis):
                    outs, new_aux = graph({**p, **batch}, aux, sub, True)
                return outs, new_aux

            outs, vjp_fn, new_aux = jax.vjp(f, params, has_aux=True)
            head = tuple(jnp.ones_like(o) for o in outs)
            return vjp_fn(head)[0], new_aux, outs

        def train_step(params, opt_state, aux, batch, key, lr_scale):
            # split inside the step: the whole key chain lives on-device,
            # so each step is ONE program dispatch (a separate host-side
            # split program adds a dispatch gap per step)
            key, sub = jax.random.split(key)
            if n_accum == 1:
                grads, new_aux, outs = grads_of(params, aux, batch, sub)
            else:
                # pin each microbatch's own batch dim to the original
                # input sharding (accum axis replicated) — otherwise the
                # partitioner may shard the scan axis and insert
                # per-microbatch collectives
                micro = {
                    k: jax.lax.with_sharding_constraint(
                        v.reshape((n_accum, v.shape[0] // n_accum)
                                  + v.shape[1:]),
                        NamedSharding(self.mesh, PartitionSpec(
                            None, *self.batch_shardings[k].spec)))
                    for k, v in batch.items()}

                def body(carry, mb):
                    g_acc, aux_c, key_c = carry
                    key_c, s = jax.random.split(key_c)
                    g, aux_n, outs_mb = grads_of(params, aux_c, mb, s)
                    g_acc = jax.tree_util.tree_map(jnp.add, g_acc, g)
                    return (g_acc, aux_n, key_c), outs_mb

                zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
                (grads, new_aux, sub), outs_st = jax.lax.scan(
                    body, (zeros, aux, sub), micro)
                # microbatch outputs stacked on a leading accum axis.
                # ASSUMPTION: head outputs are per-sample batch-leading
                # (the SoftmaxOutput/MakeLoss contract this trainer
                # targets) or scalar.  Batch-leading outputs flatten back
                # to the global batch for metrics; scalar heads combine
                # by SUM — consistent with the un-normalized loss
                # contract (rescale_grad=1/global_batch assumes
                # sum-losses); a mean-reduced head will read differently
                # across accumulation settings.
                outs = tuple(
                    jnp.sum(o, axis=0) if o.ndim == 1
                    else o.reshape((-1,) + o.shape[2:])
                    for o in outs_st)
            scale = self._rescale_grad
            grads = {k: g * scale for k, g in grads.items()}
            new_params, new_opt = self._update_fn(grads, opt_state, params,
                                                  lr_scale=lr_scale)
            return new_params, new_opt, new_aux, outs, key

        def eval_step(params, aux, batch, key):
            with spmd_attention(mesh, batch_axis, seq_axis):
                outs, _ = graph({**params, **batch}, aux, key, False)
            return outs

        p_shard = self.param_shardings
        rep = self._replicated
        opt_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, self.opt_state)
        aux_shardings = {k: rep for k in self.aux_names}
        self._train_step = jax.jit(
            train_step,
            in_shardings=(p_shard, opt_shardings, aux_shardings,
                          self.batch_shardings, rep, rep),
            out_shardings=(p_shard, opt_shardings, aux_shardings, None, rep),
            donate_argnums=(0, 1, 2),
        )
        self._eval_step = jax.jit(
            eval_step,
            in_shardings=(p_shard, aux_shardings, self.batch_shardings, rep),
        )

    def _place_batch(self, batch):
        placed = {}
        for name in self.input_names:
            v = batch[name]
            if isinstance(v, nd.NDArray):
                # mxtpu-lint: disable=host-sync (host batch ingestion —
                # the input pipeline hands over host arrays here)
                v = v.asnumpy()
            # mxtpu-lint: disable=host-sync (host batch ingestion)
            v = np.asarray(v, dtype=self._input_dtypes[name])
            placed[name] = jax.device_put(v, self.batch_shardings[name])
        return placed

    def _lr_scale(self):
        """Host-side schedule evaluation -> traced scalar multiplier."""
        self._num_update += 1
        if self._lr_scheduler is None:
            return np.float32(1.0)
        # mxtpu-lint: disable=host-sync (host-side Python schedule —
        # no device value ever flows through the lr scheduler)
        lr = float(self._lr_scheduler(self._num_update))
        return np.float32(lr / max(self._base_lr, 1e-30))

    @hot_path
    def step(self, batch: dict):
        """One optimizer step on a global batch; returns outputs."""
        t0 = time.perf_counter()
        with telemetry.span("trainer.step"):
            placed = self._place_batch(batch)
            self.params, self.opt_state, self.aux, outs, self._key = \
                self._train_step(self.params, self.opt_state, self.aux,
                                 placed, self._key, self._lr_scale())
        self._tel_step_secs.observe(time.perf_counter() - t0)
        self._tel_steps.inc()
        return outs

    def eval(self, batch: dict):
        self._key, sub = jax.random.split(self._key)
        return self._eval_step(self.params, self.aux, self._place_batch(batch), sub)

    def get_params(self):
        """Gather params to host as name->np.ndarray (checkpoint surface)."""
        return {k: np.asarray(jax.device_get(v)) for k, v in self.params.items()}

    def set_params(self, arg_params):
        for k, v in arg_params.items():
            if k in self.params:
                self.params[k] = jax.device_put(
                    np.asarray(v).astype(self._dtype), self.param_shardings[k])

    # ------------------------------------------------------------------ #
    # training-loop conveniences (FeedForward.fit surface at trainer
    # level, with TPU-style host/device overlap)

    def fit(self, train_iter, num_epochs=1, eval_metric=None,
            batch_end_callback=None, epoch_end_callback=None):
        """Epoch loop with double-buffered host->device staging: batch
        n+1 is placed (host copy + transfer) on a prefetch thread while
        step n's XLA program runs — the trainer-level analog of the
        reference's PrefetchingIter + async engine overlap
        (io/iter_prefetcher.h; python/mxnet/model.py:87-115)."""
        import queue
        import threading

        from .. import ndarray as _nd
        from ..metric import create as metric_create

        metric = (metric_create(eval_metric)
                  if isinstance(eval_metric, str) else eval_metric)
        for epoch in range(num_epochs):
            train_iter.reset()
            if metric is not None:
                metric.reset()
            q = queue.Queue(maxsize=2)

            def produce():
                try:
                    for batch in train_iter:
                        feed = {}
                        for desc, arr in zip(train_iter.provide_data,
                                             batch.data):
                            feed[desc[0]] = arr
                        for desc, arr in zip(train_iter.provide_label or [],
                                             batch.label):
                            feed[desc[0]] = arr
                        # place on device from the prefetch thread: the
                        # transfer overlaps the in-flight training step
                        q.put((self._place_batch(feed), batch.label))
                    q.put(None)
                except BaseException as e:  # surface in the consumer
                    q.put(e)

            t = threading.Thread(target=produce, daemon=True)
            t.start()
            nbatch = 0
            while True:
                t0 = time.perf_counter()
                with telemetry.span("trainer.data_wait"):
                    item = q.get()
                self._tel_data_wait.observe(time.perf_counter() - t0)
                if item is None:
                    break
                if isinstance(item, BaseException):
                    t.join()
                    raise item
                placed, labels = item
                t0 = time.perf_counter()
                with telemetry.span("trainer.step"):
                    self.params, self.opt_state, self.aux, outs, self._key = \
                        self._train_step(self.params, self.opt_state,
                                         self.aux, placed, self._key,
                                         self._lr_scale())
                self._tel_step_secs.observe(time.perf_counter() - t0)
                self._tel_steps.inc()
                nbatch += 1
                if metric is not None and labels:
                    # host sync happens only when metrics are requested
                    metric.update(labels,
                                  [_nd.NDArray(o) for o in outs[:1]])
                if batch_end_callback is not None:
                    batch_end_callback(epoch, nbatch, metric)
            t.join()
            if epoch_end_callback is not None:
                epoch_end_callback(epoch, self)
        return metric

    # -- checkpointing ------------------------------------------------------
    def save_checkpoint(self, prefix, epoch=0, async_save=False):
        """Two-artifact checkpoint (reference model.save contract:
        symbol JSON + params blob) plus the optimizer state + RNG key,
        so a sharded run resumes exactly.

        ``async_save=True`` gives orbax-style semantics: the
        device->host snapshot happens now (later steps cannot corrupt
        it); serialization + file IO run on background writers with
        atomic temp-file renames (shared machinery with
        ``model.save_checkpoint``).  Call :meth:`wait_checkpoints` (or
        ``mx.model.wait_checkpoints()``) before relying on the files."""
        import pickle

        from .. import model as model_mod

        # plain-numpy snapshot: nd.save serializes numpy directly, so no
        # host->device->host round-trip for large param sets
        arg_params = self.get_params()
        aux_params = {k: np.asarray(jax.device_get(v))
                      for k, v in self.aux.items()}
        model_mod.save_checkpoint(prefix, epoch, self.symbol, arg_params,
                                  aux_params, async_save=async_save,
                                  snapshot_owned=True)
        opt_host = jax.tree_util.tree_map(
            lambda x: np.asarray(jax.device_get(x)), self.opt_state)
        # the RNG key is part of exact-resume state: dropout chains must
        # continue where the interrupted run left off
        sched_state = None
        if self._lr_scheduler is not None:
            try:
                sched_state = pickle.dumps(self._lr_scheduler)
            except Exception:
                sched_state = None  # unpicklable custom callable
                logging.warning(
                    "lr_scheduler %r is not picklable; checkpoint will "
                    "not carry scheduler state and a resumed run keeps "
                    "the live scheduler object as-is",
                    type(self._lr_scheduler).__name__)
        blob = pickle.dumps({"opt_state": opt_host,
                             "rng_key": np.asarray(jax.device_get(self._key)),
                             "num_update": self._num_update,
                             "lr_scheduler": sched_state})
        states_name = f"{prefix}-{epoch:04d}.states"

        def write_states(path):
            with open(path, "wb") as f:
                f.write(blob)

        if async_save:
            model_mod.stage_async_write(states_name, write_states)
        else:
            write_states(states_name)

    def wait_checkpoints(self):
        """Block until in-flight async checkpoint writes are on disk,
        surfacing any write failure (per-file attribution)."""
        from .. import model as model_mod

        model_mod.wait_checkpoints()

    def load_checkpoint(self, prefix, epoch=0):
        """Restore params, aux and optimizer state with the trainer's
        shardings re-applied."""
        import pickle

        from .. import ndarray as nd

        loaded = nd.load(f"{prefix}-{epoch:04d}.params")
        self.set_params({k[4:]: v.asnumpy() for k, v in loaded.items()
                         if k.startswith("arg:")})
        for k, v in loaded.items():
            if k.startswith("aux:") and k[4:] in self.aux:
                self.aux[k[4:]] = jax.device_put(v.asnumpy(),
                                                 self._replicated)
        with open(f"{prefix}-{epoch:04d}.states", "rb") as f:
            blob = pickle.loads(f.read())
        opt_host = blob["opt_state"] if isinstance(blob, dict) else blob
        self.opt_state = jax.tree_util.tree_map(
            lambda host, cur: jax.device_put(
                np.asarray(host).astype(cur.dtype), cur.sharding),
            opt_host, self.opt_state)
        if isinstance(blob, dict) and "rng_key" in blob:
            self._key = jax.device_put(blob["rng_key"], self._replicated)
        if isinstance(blob, dict):
            self._num_update = int(blob.get("num_update", self._num_update))
            if (blob.get("lr_scheduler") is not None
                    and self._lr_scheduler is not None):
                # stateful schedulers (factor counters) rewind with the
                # checkpoint; without this an earlier checkpoint would
                # resume at a permanently-decayed lr.  Guarded on the
                # trainer HAVING a scheduler: a trainer built with
                # lr_scheduler=None (constant-lr fine-tune) must not
                # silently inherit the checkpointed schedule
                self._lr_scheduler = pickle.loads(blob["lr_scheduler"])

    # -- sharded (per-host) checkpointing -----------------------------------
    def save_checkpoint_sharded(self, ckpt_dir, epoch=0, async_save=False):
        """Pod-scale checkpoint: every process writes only its local
        shards (peak host memory = largest local shard, multi-host saves
        are parallel), via :mod:`mxnet_tpu.parallel.checkpoint`.  The
        dense two-artifact path (:meth:`save_checkpoint`) stays the
        portable/interop format; this one is for state that should never
        be gathered.  Restore may use a different mesh/sharding."""
        import base64
        import pickle

        from . import checkpoint as ckpt

        step_dir = os.path.join(ckpt_dir, f"step-{epoch:04d}")
        extra = {"num_update": self._num_update, "epoch": int(epoch)}
        if self._lr_scheduler is not None:
            try:
                extra["lr_scheduler"] = base64.b64encode(
                    pickle.dumps(self._lr_scheduler)).decode("ascii")
            except Exception:
                logging.warning(
                    "lr_scheduler %r is not picklable; sharded checkpoint "
                    "will not carry scheduler state",
                    type(self._lr_scheduler).__name__)
        ckpt.save_sharded(step_dir, self._ckpt_tree(), extra=extra,
                          async_save=async_save)
        if jax.process_index() == 0:
            self.symbol.save(os.path.join(step_dir, "symbol.json"))

    def load_checkpoint_sharded(self, ckpt_dir, epoch=0):
        """Restore a :meth:`save_checkpoint_sharded` checkpoint into this
        trainer's own layout (resharding from the saved layout as
        needed)."""
        import base64
        import pickle

        from . import checkpoint as ckpt

        step_dir = os.path.join(ckpt_dir, f"step-{epoch:04d}")
        state, extra = ckpt.load_sharded(step_dir, self._ckpt_tree())
        self.params = state["params"]
        self.opt_state = state["opt_state"]
        self.aux = state["aux"]
        self._key = state["rng_key"]
        if extra:
            self._num_update = int(extra.get("num_update",
                                             self._num_update))
            if (extra.get("lr_scheduler") is not None
                    and self._lr_scheduler is not None):
                self._lr_scheduler = pickle.loads(
                    base64.b64decode(extra["lr_scheduler"]))

    def _ckpt_tree(self):
        return {"params": self.params, "opt_state": self.opt_state,
                "aux": self.aux, "rng_key": self._key}
