"""Ring attention: sequence/context parallelism for long sequences.

New first-class TPU capability (absent in the reference — SURVEY.md §2.4
marks sequence parallelism "No"; its long-sequence story was bucketing +
fused RNN).  Implements blockwise ring attention (Liu et al.: each chip
holds one sequence shard of Q/K/V; K/V shards rotate around the ring via
``ppermute`` over ICI while each chip accumulates its Q-block's attention
with streaming log-sum-exp renormalization).  Peak memory per chip is
O(S/n * S/n) instead of O(S^2); communication fully overlaps compute on
the ring.

Exposed as ``ring_attention(q, k, v, mesh, axis)`` — a jitted sharded
call (the single-device symbol-graph entry is ``mx.sym.FlashAttention``,
ops/attention.py; ``parallel/ulysses.py`` is the all-to-all variant).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["ring_attention", "attention_reference"]


def _block_attn(q, k, v, scale, causal_mask=None):
    """Scores for one (Q-block, K-block) pair with running-max stats.

    Returns (unnormalized out, row max, row sumexp)."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal_mask is not None:
        s = jnp.where(causal_mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)
    # fully-masked rows have m = -inf; subtract a finite stand-in so
    # exp(-inf - m_safe) = 0 instead of NaN
    m_safe = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m_safe[..., None])
    l = jnp.sum(p, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return o, m, l


def _ring_body(q, k, v, axis_name, n_shards, scale, causal, q_index,
               window=0, n_steps=None):
    """Per-shard ring loop: rotate K/V, accumulate with LSE renorm."""
    B, H, S_blk, D = q.shape
    if k.shape[1] != H:
        # grouped-query k/v through the dense fallback: expand here.
        # (The flash body passes reduced K/V to the kernel, which
        # groups natively under bshd; under bhsd the kernel expands
        # internally per step — still reduced traffic on the ring's
        # ppermutes either way.)
        from ..ops.flash_attention import gqa_group
        rep = gqa_group(H, k.shape[1])
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)

    def step(carry, i):
        k_cur, v_cur, o_acc, m_acc, l_acc = carry
        if causal or window:
            # the kernel's own global-position band mask (ONE source of
            # the causal/window semantics — plain jnp, works outside
            # pallas too), with the current K/V shard's offset
            from ..ops.flash_attention import _mask_for

            kv_index = (q_index - i) % n_shards
            mask = _mask_for(0, 0, S_blk, S_blk, causal,
                             q_index * S_blk, kv_index * S_blk, window)
            mask = jnp.broadcast_to(mask, (B, H, S_blk, S_blk))
        else:
            mask = None
        o_blk, m_blk, l_blk = _block_attn(q, k_cur, v_cur, scale, mask)
        # streaming renormalization
        m_new = jnp.maximum(m_acc, m_blk)
        # guard -inf blocks (fully masked): exp(-inf - -inf) -> use where
        c_acc = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_new), 0.0)
        c_blk = jnp.where(jnp.isfinite(m_blk), jnp.exp(m_blk - m_new), 0.0)
        o_new = o_acc * c_acc[..., None] + o_blk * c_blk[..., None]
        l_new = l_acc * c_acc + l_blk * c_blk
        # rotate K/V around the ring (ICI neighbor exchange)
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, o_new, m_new, l_new), None

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((B, H, S_blk), -jnp.inf, q.dtype)
    l0 = jnp.zeros((B, H, S_blk), q.dtype)
    (k, v, o, m, l), _ = lax.scan(step, (k, v, o0, m0, l0),
                                  jnp.arange(n_steps or n_shards))
    return o / jnp.maximum(l, 1e-20)[..., None]


def _ring_body_flash(q, k, v, axis_name, n_shards, scale, causal, q_index,
                     block_q, block_k, interpret, layout="bhsd", window=0,
                     n_steps=None):
    """Ring loop where each shard-pair attention block is the fused
    Pallas flash kernel (ops/flash_attention.py); per-step normalized
    outputs are stream-combined via their log-sum-exps.  The kernel's
    causal mask uses global positions = shard_index * S_blk + local, so
    diagonal / past / future K-V shards all fall out of one kernel.

    ``layout="bshd"`` keeps shards sequence-major end to end (the
    kernel indexes the head dim; the only reshuffle is the tiny
    D-free log-sum-exp row map)."""
    from ..ops.flash_attention import flash_attention

    bshd = layout == "bshd"
    if bshd:
        B, S_blk, H, D = q.shape
        row0 = (B, S_blk, H)
    else:
        B, H, S_blk, D = q.shape
        row0 = (B, H, S_blk)

    def step(carry, i):
        k_cur, v_cur, o_acc, m_acc, l_acc = carry
        kv_index = (q_index - i) % n_shards
        o_b, lse_b = flash_attention(
            q, k_cur, v_cur, causal=causal, scale=scale,
            block_q=block_q, block_k=block_k,
            q_offset=q_index * S_blk, k_offset=kv_index * S_blk,
            return_lse=True, interpret=interpret, layout=layout,
            window=window)
        if bshd:
            # lse is (B, H, S); the output rows are (B, S, H)
            lse_b = jnp.moveaxis(lse_b, 1, 2)
        # streaming logsumexp-weighted combine of normalized outputs;
        # accumulate in float32 regardless of input dtype (bf16 inputs
        # would otherwise promote the scan carry and break its type)
        m_new = jnp.maximum(m_acc, lse_b)
        c_acc = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_new), 0.0)
        c_b = jnp.exp(lse_b - m_new)
        o_new = o_acc * c_acc[..., None] + \
            o_b.astype(jnp.float32) * c_b[..., None]
        l_new = l_acc * c_acc + c_b
        perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, o_new, m_new, l_new), None

    o0 = jnp.zeros(q.shape, jnp.float32)
    m0 = jnp.full(row0, -jnp.inf, jnp.float32)
    l0 = jnp.zeros(row0, jnp.float32)
    (k, v, o, m, l), _ = lax.scan(step, (k, v, o0, m0, l0),
                                  jnp.arange(n_steps or n_shards))
    return (o / jnp.maximum(l, 1e-20)[..., None]).astype(q.dtype)


@functools.lru_cache(maxsize=64)
def _build_ring_run(mesh: Mesh, axis: str, scale: float, causal: bool,
                    impl: str, block_q: int, block_k: int, interpret: bool,
                    layout: str = "bhsd", batch_axis=None, window=0,
                    n_steps=None):
    """Cached compiled ring-attention program per (mesh, axis, config) —
    jax.jit caches on function identity, so the shard_map must be built
    once per config or every call recompiles."""
    n_shards = mesh.shape[axis]
    bshd = layout == "bshd"
    spec = _ring_spec(layout, axis, batch_axis)

    @jax.jit
    def run(q, k, v):
        def shard_fn(q_s, k_s, v_s):
            idx = lax.axis_index(axis)
            if impl == "flash":
                return _ring_body_flash(q_s, k_s, v_s, axis, n_shards, scale,
                                        causal, idx, block_q, block_k,
                                        interpret, layout=layout,
                                        window=window, n_steps=n_steps)
            if bshd:
                # dense fallback computes in BHSD; transpose at the
                # shard boundary (correctness path, not the TPU path)
                o = _ring_body(q_s.transpose(0, 2, 1, 3),
                               k_s.transpose(0, 2, 1, 3),
                               v_s.transpose(0, 2, 1, 3),
                               axis, n_shards, scale, causal, idx,
                               window=window, n_steps=n_steps)
                return o.transpose(0, 2, 1, 3)
            return _ring_body(q_s, k_s, v_s, axis, n_shards, scale, causal,
                              idx, window=window, n_steps=n_steps)

        return shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)

    return run


def _ring_spec(layout, axis, batch_axis=None):
    """The one seq-sharded PartitionSpec both the shard_map and the
    caller-side device_put use — they must never desync.  With
    ``batch_axis`` the batch dim is additionally dp-sharded (combined
    dp x sp mesh: each dp replica's sp group runs its own ring — the
    ppermutes stay inside the sp axis)."""
    if layout == "bshd":
        return PartitionSpec(batch_axis, axis, None, None)
    return PartitionSpec(batch_axis, None, axis, None)


_FLASH_AVAILABLE = {}


def _flash_available(layout="bhsd"):
    """One-time probe PER LAYOUT: compile+run the Pallas kernel on a
    tiny shape so 'auto' can fall back to the XLA body if Mosaic
    lowering fails on this backend/driver combo rather than erroring
    mid-training.  The bhsd (flattened 3D) and bshd (4D head-indexed
    BlockSpec) lowerings are distinct programs, so each layout is
    probed separately."""
    if layout not in _FLASH_AVAILABLE:
        try:
            from ..ops.flash_attention import flash_attention

            # ensure_compile_time_eval: ring_attention is routinely
            # called inside a jitted train step, where a plain probe
            # would be staged into the outer trace (never actually
            # compiled/run here) and block_until_ready on the tracer
            # would no-op — caching True without exercising Mosaic.
            # head_dim 128 matches the MXU lane layout real models use.
            with jax.ensure_compile_time_eval():
                shape = ((1, 128, 1, 128) if layout == "bshd"
                         else (1, 1, 128, 128))   # S=128, H=1 either way
                x = jnp.zeros(shape, jnp.float32)
                jax.block_until_ready(
                    flash_attention(x, x, x, layout=layout))
            _FLASH_AVAILABLE[layout] = True
        except Exception:
            _FLASH_AVAILABLE[layout] = False
    return _FLASH_AVAILABLE[layout]


def ring_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal=False,
                   impl="auto", block_q=512, block_k=512, layout="bhsd",
                   batch_axis=None, window=0):
    """Sharded multi-head attention over a sequence-parallel mesh axis.

    q/k/v: (batch, heads, seq, head_dim) for ``layout="bhsd"`` or
    (batch, seq, heads, head_dim) for ``layout="bshd"`` (sequence-major
    — shards feed the flash kernel with zero activation transposes),
    sharded over ``axis`` on the seq dimension (replicated arrays are
    accepted and sharded here).  Returns the attention output with the
    same layout and sharding.

    impl: "flash" runs each shard-pair block through the fused Pallas
    kernel; "xla" uses the jnp blockwise body; "auto" picks flash on
    TPU (when the shard length divides the kernel block sizes) and xla
    elsewhere.  K/V may carry fewer heads than q (grouped-query
    attention): the flash body streams the reduced K/V shards around
    the ring natively — the GQA traffic saving applies to the ring
    ppermutes too — and the dense body expands.

    batch_axis: optional dp mesh axis the batch dim is ALSO sharded
    over (combined dp x sp data+sequence parallelism); each dp
    replica's sp group runs an independent ring.
    """
    from ..ops.flash_attention import _on_tpu

    if layout not in ("bhsd", "bshd"):
        raise ValueError(f"layout must be 'bhsd' or 'bshd', got {layout!r}")
    seq_axis = 1 if layout == "bshd" else 2
    scale = float(1.0 / np.sqrt(q.shape[-1]))
    n_shards = mesh.shape[axis]
    S_blk = q.shape[seq_axis] // n_shards
    interpret = not _on_tpu()
    if impl == "auto":
        from ..ops.flash_attention import flash_eligible
        fits = flash_eligible(S_blk, S_blk, block_q, block_k)
        impl = ("flash" if (not interpret and fits
                            and _flash_available(layout))
                else "xla")
    if window < 0:
        raise ValueError(f"ring_attention: window must be >= 0 "
                         f"(got {window})")
    n_steps = None
    if window and causal:
        # sliding-window + causal bounds the ring: a K/V shard i steps
        # back is entirely below the band once (i-1)*S_blk + 1 >= window
        # (min q-k distance between the shards), so only the diagonal
        # and ceil((window-1)/S_blk) predecessors can contribute — at
        # long S with small windows the ring shrinks to neighbor
        # exchanges (the point of windowed attention over shards)
        import math
        n_steps = min(n_shards, 1 + math.ceil((window - 1) / S_blk))
    run = _build_ring_run(mesh, axis, scale, bool(causal), impl,
                          block_q, block_k, interpret, layout, batch_axis,
                          int(window), n_steps)

    if not isinstance(q, jax.core.Tracer):
        sharding = NamedSharding(mesh, _ring_spec(layout, axis, batch_axis))
        q = jax.device_put(q, sharding)
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
    return run(q, k, v)


def attention_reference(q, k, v, causal=False):
    """Dense single-device attention for testing."""
    scale = float(1.0 / np.sqrt(q.shape[-1]))
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        S = q.shape[2]
        mask = np.tril(np.ones((S, S), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)
