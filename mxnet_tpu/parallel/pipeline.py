"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference had no explicit pipeline schedule — overlap emerged from
the dependency engine running different layers' ops on different devices
(SURVEY.md §2.4 "Pipeline parallelism: implicit only").  This module is
the explicit TPU-native upgrade: each device on the ``pp`` mesh axis owns
one stage's parameters; microbatches stream through the ring via
``ppermute`` (ICI neighbor transfers) with a ``lax.scan`` over schedule
ticks, so the whole pipeline — including the bubble — is one compiled
XLA program, differentiable end to end (reverse-mode replays the
schedule backwards).

Requirements: homogeneous stages (same activation shape in/out), stage
parameters stacked on a leading axis sharded over ``pp``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["pipeline_apply", "PipelineModule"]


@functools.lru_cache(maxsize=64)
def _build_pipeline_run(stage_fn, mesh: Mesh, axis: str):
    """Cached compiled pipeline program per (stage_fn, mesh, axis) —
    jax.jit caches on function identity, so the shard_map must be built
    once per config or every call recompiles."""
    n_stages = mesh.shape[axis]
    rep = PartitionSpec()

    def shard_fn(params, feed_local):
        # params: this device's stage slice, leading dim 1
        params_i = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1

        def tick(carry, feed_t):
            state, ys = carry
            inp = jnp.where(is_first, feed_t, state)
            out = stage_fn(params_i, inp)
            # shift to the next stage; last stage's send wraps but is unused
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            state_next = lax.ppermute(out, axis, perm)
            return (state_next, out), out

        state0 = jnp.zeros_like(feed_local[0])
        ys0 = jnp.zeros_like(feed_local[0])
        (_, _), outs = lax.scan(tick, (state0, ys0), feed_local)
        # last stage's outputs for ticks [n_stages-1, total) are the results
        result = outs[n_stages - 1:]
        # replicate the last stage's result to every device
        result = lax.psum(jnp.where(is_last, result, jnp.zeros_like(result)),
                          axis)
        return result

    @jax.jit
    def run(stacked_params, feed):
        p_spec = jax.tree_util.tree_map(lambda _: PartitionSpec(axis),
                                        stacked_params)
        return shard_map(shard_fn, mesh=mesh, in_specs=(p_spec, rep),
                         out_specs=rep, check_vma=False)(stacked_params, feed)

    return run


def pipeline_apply(stage_fn, stacked_params, x, n_microbatches, mesh: Mesh,
                   axis: str = "pp"):
    """Run ``x`` through ``n_stages`` copies of ``stage_fn`` as a pipeline.

    Parameters
    ----------
    stage_fn : (params_i, activation) -> activation, same shape in/out;
        must be a stable function object for compile caching
    stacked_params : pytree whose leaves have leading dim n_stages
        (sharded over ``axis``; each device sees its own stage's slice)
    x : (batch, ...) global input; split into n_microbatches along batch
    n_microbatches : must divide batch
    """
    n_stages = mesh.shape[axis]
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError("batch not divisible by n_microbatches")
    mb = B // n_microbatches
    xs = x.reshape((n_microbatches, mb) + x.shape[1:])
    pad = jnp.zeros((n_stages - 1,) + xs.shape[1:], xs.dtype)
    feed = jnp.concatenate([xs, pad], axis=0)  # one injection per tick

    run = _build_pipeline_run(stage_fn, mesh, axis)
    outs = run(stacked_params, feed)
    return outs.reshape((B,) + x.shape[1:])


class PipelineModule:
    """Convenience wrapper: N identical stages + heads, trainable.

    ``stage_fn(params_i, x) -> x`` applied pipeline-parallel, with a
    user ``loss_fn(final_activation, labels) -> scalar`` for training.
    """

    def __init__(self, stage_fn, stacked_params, mesh, axis="pp",
                 n_microbatches=4):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_microbatches = n_microbatches
        self._steps = {}               # (loss_fn id) -> jitted update
        spec = jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, PartitionSpec(axis)), stacked_params)
        self.params = jax.device_put(stacked_params, spec)

    def forward(self, x):
        return pipeline_apply(self.stage_fn, self.params, x,
                              self.n_microbatches, self.mesh, self.axis)

    def _make_objective(self, loss_fn, x):
        def objective(params):
            out = pipeline_apply(self.stage_fn, params, x,
                                 self.n_microbatches, self.mesh, self.axis)
            return loss_fn(out)

        return objective

    def grad_step(self, x, loss_fn, lr=0.01):
        """One SGD step through the pipelined computation.

        ``loss_fn`` must be a stable function object — the jitted update
        is cached per loss_fn, so a fresh lambda per call recompiles."""
        from .trainer import cached_sgd_step

        step = cached_sgd_step(self._steps, loss_fn, self._make_objective)
        loss, _, self.params = step(self.params, x, lr)
        return loss
