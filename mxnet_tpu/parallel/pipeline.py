"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

The reference had no explicit pipeline schedule — overlap emerged from
the dependency engine running different layers' ops on different devices
(SURVEY.md §2.4 "Pipeline parallelism: implicit only").  This module is
the explicit TPU-native upgrade: each device on the ``pp`` mesh axis owns
one stage's parameters; microbatches stream through the ring via
``ppermute`` (ICI neighbor transfers) with a ``lax.scan`` over schedule
ticks, so the whole pipeline — including the bubble — is one compiled
XLA program, differentiable end to end (reverse-mode replays the
schedule backwards).

Requirements: homogeneous stages (same activation shape in/out), stage
parameters stacked on a leading axis sharded over ``pp``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["pipeline_apply", "PipelineModule"]


# (stage_fn, mesh, axis, flat specs, treedef, feed/out specs) -> jitted run.
# jax.jit caches on function identity, so the shard_map must be built once
# per config or every call recompiles; specs form pytrees (unhashable by
# lru_cache), hence the explicit dict.
_RUN_CACHE: dict = {}


def _build_pipeline_run(stage_fn, mesh: Mesh, axis: str, param_specs=None,
                        feed_spec=None, out_spec=None):
    """Compiled pipeline program, optionally composed with other mesh
    axes: ``param_specs`` (pytree of PartitionSpec, leading dim = stage
    axis) lets stage weights shard over e.g. ``tp``; ``feed_spec`` /
    ``out_spec`` shard the microbatch feed (e.g. batch over ``dp``).
    The stage_fn is then free to use explicit collectives
    (``lax.psum(..., 'tp')``) — megatron-inside-GPipe composition."""
    rep = PartitionSpec()
    if feed_spec is None:
        feed_spec = rep
    if out_spec is None:
        out_spec = feed_spec
    if param_specs is None:
        p_spec = None
        key_specs = None
    else:
        flat, treedef = jax.tree_util.tree_flatten(param_specs)
        p_spec = param_specs
        key_specs = (tuple(flat), treedef)
    key = (stage_fn, mesh, axis, key_specs, feed_spec, out_spec)
    if key in _RUN_CACHE:
        return _RUN_CACHE[key]
    # bounded like the lru_cache it replaced: fresh stage_fn lambdas at
    # call sites would otherwise pin compiled programs forever
    while len(_RUN_CACHE) >= 64:
        _RUN_CACHE.pop(next(iter(_RUN_CACHE)))

    n_stages = mesh.shape[axis]

    def shard_fn(params, feed_local):
        # params: this device's stage slice, leading dim 1
        params_i = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = lax.axis_index(axis)
        is_first = idx == 0
        is_last = idx == n_stages - 1

        def tick(carry, feed_t):
            state, ys = carry
            inp = jnp.where(is_first, feed_t, state)
            out = stage_fn(params_i, inp)
            # shift to the next stage; last stage's send wraps but is unused
            perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
            state_next = lax.ppermute(out, axis, perm)
            return (state_next, out), out

        state0 = jnp.zeros_like(feed_local[0])
        ys0 = jnp.zeros_like(feed_local[0])
        (_, _), outs = lax.scan(tick, (state0, ys0), feed_local)
        # last stage's outputs for ticks [n_stages-1, total) are the results
        result = outs[n_stages - 1:]
        # replicate the last stage's result to every device
        result = lax.psum(jnp.where(is_last, result, jnp.zeros_like(result)),
                          axis)
        return result

    @jax.jit
    def run(stacked_params, feed):
        if p_spec is None:
            spec = jax.tree_util.tree_map(lambda _: PartitionSpec(axis),
                                          stacked_params)
        else:
            spec = p_spec
        return shard_map(shard_fn, mesh=mesh, in_specs=(spec, feed_spec),
                         out_specs=out_spec, check_vma=False)(stacked_params,
                                                              feed)

    _RUN_CACHE[key] = run
    return run


def pipeline_apply(stage_fn, stacked_params, x, n_microbatches, mesh: Mesh,
                   axis: str = "pp", param_specs=None, feed_spec=None,
                   out_spec=None):
    """Run ``x`` through ``n_stages`` copies of ``stage_fn`` as a pipeline.

    Parameters
    ----------
    stage_fn : (params_i, activation) -> activation, same shape in/out;
        must be a stable function object for compile caching
    stacked_params : pytree whose leaves have leading dim n_stages
        (sharded over ``axis``; each device sees its own stage's slice)
    x : (batch, ...) global input; split into n_microbatches along batch
    n_microbatches : must divide batch
    """
    n_stages = mesh.shape[axis]
    B = x.shape[0]
    if B % n_microbatches:
        raise ValueError("batch not divisible by n_microbatches")
    mb = B // n_microbatches
    xs = x.reshape((n_microbatches, mb) + x.shape[1:])
    pad = jnp.zeros((n_stages - 1,) + xs.shape[1:], xs.dtype)
    feed = jnp.concatenate([xs, pad], axis=0)  # one injection per tick

    run = _build_pipeline_run(stage_fn, mesh, axis, param_specs, feed_spec,
                              out_spec)
    outs = run(stacked_params, feed)
    return outs.reshape((B,) + x.shape[1:])


class PipelineModule:
    """Convenience wrapper: N identical stages + heads, trainable.

    ``stage_fn(params_i, x) -> x`` applied pipeline-parallel, with a
    user ``loss_fn(final_activation, labels) -> scalar`` for training.
    """

    def __init__(self, stage_fn, stacked_params, mesh, axis="pp",
                 n_microbatches=4, param_specs=None, feed_spec=None,
                 out_spec=None):
        self.stage_fn = stage_fn
        self.mesh = mesh
        self.axis = axis
        self.n_microbatches = n_microbatches
        self.param_specs = param_specs
        self.feed_spec = feed_spec
        self.out_spec = out_spec
        self._steps = {}               # (loss_fn id) -> jitted update
        if param_specs is None:
            spec = jax.tree_util.tree_map(
                lambda _: NamedSharding(mesh, PartitionSpec(axis)),
                stacked_params)
        else:
            spec = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), param_specs)
        self.params = jax.device_put(stacked_params, spec)

    def forward(self, x):
        return pipeline_apply(self.stage_fn, self.params, x,
                              self.n_microbatches, self.mesh, self.axis,
                              self.param_specs, self.feed_spec, self.out_spec)

    def _make_objective(self, loss_fn, x):
        def objective(params):
            out = pipeline_apply(self.stage_fn, params, x,
                                 self.n_microbatches, self.mesh, self.axis,
                                 self.param_specs, self.feed_spec,
                                 self.out_spec)
            return loss_fn(out)

        return objective

    def grad_step(self, x, loss_fn, lr=0.01):
        """One SGD step through the pipelined computation.

        ``loss_fn`` must be a stable function object — the jitted update
        is cached per loss_fn, so a fresh lambda per call recompiles."""
        from .trainer import cached_sgd_step

        # mxtpu-lint: donates=0 (params buffers reused in place on TPU)
        step = cached_sgd_step(self._steps, loss_fn, self._make_objective)
        loss, _, self.params = step(self.params, x, lr)
        return loss
