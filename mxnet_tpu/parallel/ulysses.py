"""Ulysses-style all-to-all sequence parallelism.

New first-class TPU capability (absent in the reference — SURVEY.md §2.4
marks sequence parallelism "No").  Complement to ring attention
(``parallel/ring_attention.py``): instead of rotating K/V shards around
the ring, two ``all_to_all`` collectives re-shard the activations from
sequence-parallel to head-parallel layout and back:

    (B, H, S/n, D)  --all_to_all-->  (B, H/n, S, D)
         attention over the FULL sequence per local head group
    (B, H/n, S, D)  --all_to_all-->  (B, H, S/n, D)

Each chip then runs an ordinary (flash) attention over its head subset,
so the attention inner loop needs no per-step communication — the
tradeoff vs the ring is 2 all-to-alls of activation size against n
ppermutes of K/V size, and the head count must divide the mesh axis.

API mirrors ``ring_attention``: ``ulysses_attention(q, k, v, mesh,
axis, causal, impl, layout)`` with q/k/v (batch, heads, seq, head_dim)
for ``layout="bhsd"`` or sequence-major (batch, seq, heads, head_dim)
for ``layout="bshd"`` (the all-to-alls split/concat the same two axes
in either order, so BSHD stays transpose-free end to end), sharded
over ``axis`` on the sequence dimension.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding

__all__ = ["ulysses_attention"]


def _dense_attention(q, k, v, scale, causal, window=0):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal or window:
        # the kernel's band-mask helper is the single source of the
        # causal/window semantics
        from ..ops.flash_attention import _mask_for

        S = q.shape[2]
        s = jnp.where(_mask_for(0, 0, S, S, causal, 0, 0, window),
                      s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.lru_cache(maxsize=64)
def _build_ulysses_run(mesh: Mesh, axis: str, scale: float, causal: bool,
                       impl: str, block_q: int, block_k: int,
                       interpret: bool, layout: str = "bhsd",
                       batch_axis=None, window=0):
    """Cached compiled program per (mesh, axis, config) — same caching
    contract as ring_attention's _build_ring_run."""
    from .ring_attention import _ring_spec

    bshd = layout == "bshd"
    spec = _ring_spec(layout, axis, batch_axis)
    # the all-to-all trades the sharded axis for the head axis; both
    # layouts keep their own order end to end (bshd: seq=1, heads=2)
    seq_ax, head_ax = (1, 2) if bshd else (2, 1)

    @jax.jit
    def run(q, k, v):
        def shard_fn(q_s, k_s, v_s):
            # seq-sharded -> head-sharded: split heads, gather sequence
            def to_heads(x):
                return lax.all_to_all(x, axis, split_axis=head_ax,
                                      concat_axis=seq_ax, tiled=True)

            qh, kh, vh = to_heads(q_s), to_heads(k_s), to_heads(v_s)
            if kh.shape[head_ax] != qh.shape[head_ax] and impl != "flash":
                # native-GQA shards reach the dense body with fewer kv
                # heads per group; the kernel groups natively but the
                # einsum needs equal head counts — expand per shard
                rep = qh.shape[head_ax] // kh.shape[head_ax]
                kh = jnp.repeat(kh, rep, axis=head_ax)
                vh = jnp.repeat(vh, rep, axis=head_ax)
            # window passes straight through: after the all-to-all
            # each head group holds the FULL sequence, so the band
            # mask is the ordinary local one
            if impl == "flash":
                from ..ops.flash_attention import flash_attention

                oh = flash_attention(qh, kh, vh, causal=causal,
                                     block_q=block_q, block_k=block_k,
                                     interpret=interpret, layout=layout,
                                     window=window)
            elif bshd:
                oh = _dense_attention(qh.transpose(0, 2, 1, 3),
                                      kh.transpose(0, 2, 1, 3),
                                      vh.transpose(0, 2, 1, 3),
                                      scale, causal,
                                      window).transpose(0, 2, 1, 3)
            else:
                oh = _dense_attention(qh, kh, vh, scale, causal, window)
            # head-sharded -> seq-sharded: split sequence, gather heads
            return lax.all_to_all(oh, axis, split_axis=seq_ax,
                                  concat_axis=head_ax, tiled=True)

        return shard_map(
            shard_fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)(q, k, v)

    return run


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = "sp", causal=False,
                      impl="auto", block_q=512, block_k=512, layout="bhsd",
                      batch_axis=None, window=0):
    """All-to-all sequence-parallel multi-head attention.

    q/k/v: (batch, heads, seq, head_dim) for ``layout="bhsd"`` or
    (batch, seq, heads, head_dim) for ``layout="bshd"`` (sequence-major
    — the all-to-alls and the kernel preserve the order, so no
    activation transposes), sharded over ``axis`` on the sequence
    dimension (replicated arrays are accepted and sharded here).
    Requires heads %% mesh.shape[axis] == 0.  Returns the attention
    output with the same layout and sequence sharding.

    impl: "flash" = fused Pallas kernel per head group; "xla" = dense
    softmax attention; "auto" picks flash on TPU when shapes fit.
    """
    from ..ops.flash_attention import _on_tpu
    from .ring_attention import _flash_available, _ring_spec

    if layout not in ("bhsd", "bshd"):
        raise ValueError(f"layout must be 'bhsd' or 'bshd', got {layout!r}")
    head_axis, seq_axis = (2, 1) if layout == "bshd" else (1, 2)
    n_shards = mesh.shape[axis]
    H = q.shape[head_axis]
    if k.shape[head_axis] != H:
        # grouped-query k/v: the all-to-alls re-shard the HEAD axis.
        # When the kv heads ALSO divide the mesh axis the K/V
        # all-to-alls simply split the reduced axis — GQA stays native
        # (each head group attends with Hkv/sp shared K/V heads in the
        # kernel).  Otherwise expand to full heads first.
        from ..ops.flash_attention import gqa_group
        rep = gqa_group(H, k.shape[head_axis])
        if k.shape[head_axis] % n_shards:
            k = jnp.repeat(k, rep, axis=head_axis)
            v = jnp.repeat(v, rep, axis=head_axis)
    if H % n_shards != 0:
        raise ValueError(
            f"ulysses_attention: heads ({H}) must be divisible by the "
            f"'{axis}' mesh axis ({n_shards}); use ring_attention for "
            "head counts that do not divide the mesh")
    if window < 0:
        raise ValueError(f"ulysses_attention: window must be >= 0 "
                         f"(got {window})")
    scale = float(1.0 / np.sqrt(q.shape[-1]))
    S = q.shape[seq_axis]
    interpret = not _on_tpu()
    if impl == "auto":
        from ..ops.flash_attention import flash_eligible
        fits = flash_eligible(S, S, block_q, block_k)
        impl = ("flash" if (not interpret and fits
                            and _flash_available(layout))
                else "xla")
    run = _build_ulysses_run(mesh, axis, scale, bool(causal), impl,
                             block_q, block_k, interpret, layout,
                             batch_axis, int(window))

    if not isinstance(q, jax.core.Tracer):
        sharding = NamedSharding(mesh, _ring_spec(layout, axis, batch_axis))
        q = jax.device_put(q, sharding)
        k = jax.device_put(k, sharding)
        v = jax.device_put(v, sharding)
    return run(q, k, v)
