"""Regex-rule partitioning: one shared ``match_partition_rules`` for
training AND serving.

GSPMD sharding in this framework is always expressed the same way: a
pytree of parameters, an ordered list of ``(pattern, PartitionSpec)``
rules, and a mesh whose axis names the specs reference.  The rule
matcher walks the parameter names in order and returns the first
matching spec per leaf — the ``match_partition_rules`` pattern of the
GSPMD/fmengine lineage (SNIPPETS.md [2]), here keyed off the
``models.gpt()`` checkpoint naming that ``normalize_gpt_params``
guarantees.

Consumers:

- ``parallel.ShardedTrainer`` — ``param_specs`` rules resolve through
  :func:`match_partition_rules` (``mode="full"``: a key is an exact
  name or a fullmatch regex), falling back to its FSDP heuristic.
- ``serve.Engine`` — tensor-parallel serving shards the gpt()
  parameter dict with :func:`gpt_partition_rules` (or the operator's
  ``MXTPU_SERVE_PARTITION_RULES`` override parsed by
  :func:`parse_rules`) over a ``{'tp': N}`` mesh.

The default GPT rule set is the weight-stationary Megatron/TP layout
(Pope et al., *Efficiently Scaling Transformer Inference*): attention
q/k/v projections and MLP in-projections split on their output (head /
hidden) dimension, attention out-projection and MLP down-projection
split on their input dimension (their matmuls produce partial sums and
GSPMD inserts exactly two all-reduces per layer), everything else —
embeddings, norms, down-projection biases, the LM head — replicated.
"""

from __future__ import annotations

import hashlib
import json
import re

import numpy as np

from jax.sharding import NamedSharding, PartitionSpec

__all__ = ["match_partition_rules", "gpt_partition_rules", "parse_rules",
           "rules_digest", "spec_tuple", "named_shardings"]


def spec_tuple(spec):
    """A ``PartitionSpec`` as a JSON-stable tuple (axis entries may be
    None, a name, or a tuple of names)."""
    return tuple(list(e) if isinstance(e, (tuple, list)) else e
                 for e in tuple(spec))


def _matches(pattern, name, mode):
    if mode == "full":
        # ShardedTrainer's historical param_specs contract: a key is an
        # exact parameter name OR a regex that must span the whole name
        return pattern == name or re.fullmatch(pattern, name) is not None
    return re.search(pattern, name) is not None


def match_partition_rules(rules, params, default=PartitionSpec(),
                          mode="search"):
    """Resolve ``rules`` against a parameter dict.

    Args:
      rules: ordered iterable of ``(pattern, PartitionSpec)``; the
        FIRST matching pattern wins.
      params: dict name -> array-like or shape tuple (only ``.shape``
        / the tuple itself is consulted — pass shapes to partition
        before materializing anything).
      default: spec for unmatched leaves — a ``PartitionSpec``, a
        callable ``(name, shape) -> PartitionSpec`` (the trainer's FSDP
        heuristic), or the string ``"raise"`` to make an unmatched
        parameter a hard error (the fmengine contract).
      mode: ``"search"`` (``re.search``, the GSPMD-repo convention) or
        ``"full"`` (exact name or fullmatch — ShardedTrainer
        ``param_specs`` compatibility).

    Returns ``{name: PartitionSpec}``.  Unmatched scalar / one-element
    leaves are always replicated (partitioning them is meaningless);
    an explicit rule still wins over that shortcut, exactly so the
    trainer's behavior is unchanged by the refactor onto this helper.
    """
    rules = list(rules or [])
    out = {}
    for name, leaf in params.items():
        shape = getattr(leaf, "shape", leaf)
        shape = tuple(shape) if shape is not None else ()
        spec = None
        for pattern, ps in rules:
            if _matches(pattern, name, mode):
                spec = ps
                break
        if spec is None:
            if len(shape) == 0 or int(np.prod(shape)) == 1:
                spec = PartitionSpec()
            elif isinstance(default, str) and default == "raise":
                raise ValueError(
                    f"no partition rule matches parameter {name!r} "
                    f"(shape {shape})")
            elif callable(default):
                spec = default(name, shape)
            else:
                spec = default
        out[name] = spec
    return out


def gpt_partition_rules(name="gpt", axis="tp"):
    """Default tensor-parallel rule set for a ``models.gpt()``
    checkpoint normalized by ``normalize_gpt_params``.

    Head-split q/k/v (rows of the (H*Dh, D) projection are heads),
    hidden-split MLP in-projections, input-split out/down projections
    (GSPMD turns their partial-sum matmuls into the layer's two
    all-reduces), replicated embeddings/norms/LM-head.  The catch-all
    replicate rule is explicit so ``match_partition_rules`` covers
    every leaf without a fallback.
    """
    P = PartitionSpec
    L = rf"{re.escape(name)}_l\d+"
    return [
        (rf"{L}_(q|k|v)_weight$", P(axis, None)),
        (rf"{L}_(q|k|v)_bias$", P(axis)),
        (rf"{L}_proj_weight$", P(None, axis)),
        (rf"{L}_ff_(gate|up)_weight$", P(axis, None)),
        (rf"{L}_ff_(gate|up)_bias$", P(axis)),
        (rf"{L}_ff_down_weight$", P(None, axis)),
        (r".*", P()),     # embeddings, norms, proj/down bias, LM head
    ]


def parse_rules(text):
    """Parse the ``MXTPU_SERVE_PARTITION_RULES`` syntax into rules.

    One rule per ``;``-separated segment: ``<regex>=<spec>`` (split on
    the LAST ``=`` so regexes may contain one), where ``<spec>`` is a
    comma-separated axis entry per array dimension — an axis name, or
    ``-`` for an unsharded dimension.  An empty spec replicates::

        .*_(q|k|v)_weight$=tp,-;.*_proj_weight$=-,tp;.*=

    Returns a list of ``(pattern, PartitionSpec)`` (empty for empty /
    None input — callers fall back to :func:`gpt_partition_rules`).
    """
    rules = []
    for segment in (text or "").split(";"):
        segment = segment.strip()
        if not segment:
            continue
        if "=" not in segment:
            raise ValueError(
                f"bad partition rule {segment!r}: expected <regex>=<spec>")
        pattern, spec_str = segment.rsplit("=", 1)
        pattern = pattern.strip()
        entries = []
        if spec_str.strip():             # empty spec = replicate
            for entry in spec_str.split(","):
                entry = entry.strip()
                if not entry:
                    # a stray comma would silently SHIFT later axis
                    # names onto earlier dimensions — fail fast instead
                    # (unsharded dimensions are spelled '-')
                    raise ValueError(
                        f"bad partition spec {spec_str!r} in rule "
                        f"{segment!r}: empty entry (use '-' for an "
                        "unsharded dimension)")
                entries.append(None if entry == "-" else entry)
        re.compile(pattern)          # fail fast on a broken regex
        rules.append((pattern, PartitionSpec(*entries)))
    return rules


def rules_digest(rules):
    """Stable hex digest of a rule list — the AOT-fingerprint component
    that keys exported artifacts per sharding layout."""
    canon = [[pattern, list(spec_tuple(spec))] for pattern, spec in rules]
    return hashlib.sha256(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()


def named_shardings(mesh, specs):
    """{name: PartitionSpec} -> {name: NamedSharding} on ``mesh``."""
    return {name: NamedSharding(mesh, spec) for name, spec in specs.items()}
