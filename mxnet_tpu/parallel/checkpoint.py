"""Sharded (per-host) checkpointing for pod-scale trainer state.

The classic two-artifact checkpoint (``model.save_checkpoint``,
reference model.py:318-347) gathers every array to one host — fine for
reference-era model sizes, quadratically painful for pod-sharded
parameter trees where no single host can even hold the gathered state.
This module writes each array as its device shards: every process saves
only the shards it can address (one replica of each distinct shard
index), so a multi-host save is naturally parallel and each host's peak
memory is bounded by its locally-addressable state, not the global tree
(the local snapshot is held in RAM until written — the price of
async-safe point-in-time semantics).

Restore goes through ``jax.make_array_from_callback`` so the saved
layout does NOT need to match the loading layout: each device's shard
is assembled from whichever saved pieces intersect it.  That makes the
checkpoint reshardable — save on a ``dp×tp`` mesh, restore on ``tp``
only, or on a different device count (the elastic-restart story for
sharded runs; the orbax design, rebuilt minimally over npz + JSON).

Layout of a checkpoint directory::

    step-0003/
      meta-proc0.json   # per array: global shape/dtype + shard index map
      shards-proc0.npz  # the shard payloads owned by process 0
      [meta-proc1.json, shards-proc1.npz, ...]   # multi-host
      extra.json        # host-side scalars (process 0 only)

All payloads live in ``.npz`` entries keyed ``<array-key>|<n>``;
bfloat16 is stored as a tagged uint16 view (npz cannot hold bf16, same
trick as ``nd.save``).
"""

from __future__ import annotations

import json
import os

import numpy as np

import jax

from ..base import MXNetError, np_dtype

__all__ = ["save_sharded", "load_sharded"]


def _tree_leaves(tree):
    """Flatten a pytree into {stable-string-key: leaf}."""
    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[jax.tree_util.keystr(path)] = leaf
    return out


def _norm_index(index, shape):
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    norm = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        norm.append([start, stop])
    # scalar / rank-0 arrays have an empty index tuple
    return norm


def save_sharded(ckpt_dir, tree, extra=None, async_save=False):
    """Write the addressable shards of every array in ``tree`` (any
    pytree of jax.Arrays) under ``ckpt_dir``.

    ``extra`` is an optional JSON-serializable dict of host-side state
    (step counters etc.), written by process 0.  With ``async_save``
    the device->host shard snapshot happens now; file IO runs on the
    background writer shared with ``model.save_checkpoint`` (use
    ``model.wait_checkpoints()`` / ``Trainer.wait_checkpoints``).
    """
    from .. import model as model_mod

    proc = jax.process_index()
    os.makedirs(ckpt_dir, exist_ok=True)
    leaves = _tree_leaves(tree)

    meta = {}
    payload = {}
    for key, arr in leaves.items():
        arr = jax.numpy.asarray(arr)  # tolerate numpy/scalar leaves
        shards_meta = []
        n = 0
        for shard in arr.addressable_shards:
            if shard.replica_id != 0:
                continue  # exactly one copy of each distinct index
            entry = f"{key}|{n}"
            data = np.asarray(jax.device_get(shard.data))
            if data.dtype == np_dtype("bfloat16"):
                payload["__bf16__:" + entry] = data.view(np.uint16)
            else:
                payload[entry] = data
            shards_meta.append({"entry": entry, "proc": proc,
                                "index": _norm_index(shard.index, arr.shape)})
            n += 1
        meta[key] = {"shape": list(arr.shape), "dtype": str(arr.dtype),
                     "shards": shards_meta}

    meta_path = os.path.join(ckpt_dir, f"meta-proc{proc}.json")
    npz_path = os.path.join(ckpt_dir, f"shards-proc{proc}.npz")

    def write_npz(path):
        with open(path, "wb") as f:
            np.savez(f, **payload)

    def write_meta(path):
        with open(path, "w") as f:
            json.dump(meta, f)

    writers = [(npz_path, write_npz), (meta_path, write_meta)]
    if proc == 0 and extra is not None:
        blob = json.dumps(extra)
        writers.append((os.path.join(ckpt_dir, "extra.json"),
                        lambda p, b=blob: open(p, "w").write(b)))
    for path, writer in writers:
        if async_save:
            model_mod.stage_async_write(path, writer)
        else:
            writer(path + ".tmp")
            os.replace(path + ".tmp", path)


def latest_complete_step(ckpt_dir, n_procs=None):
    """Newest ``step-NNNN`` under ``ckpt_dir`` whose per-process
    artifacts are COMPLETE, or None.  Complete = every proc in
    ``range(n_procs)`` has both its meta json and shard npz (metas are
    written tmp+rename after the payload, so presence implies a whole
    shard file).  ``n_procs`` defaults to ``jax.process_count()``.

    This is the elastic gang-restart resume point (tools/launch.py
    --gang-restarts): a crash mid-save leaves the newest dir partial,
    and the job must fall back to the last step everyone finished —
    the reference tracker's restart-from-model.save analog."""
    if n_procs is None:
        n_procs = jax.process_count()
    def step_no(d):
        try:
            return int(d.split("-", 1)[1])
        except ValueError:
            return None

    try:
        # numeric sort: lexicographic would rank step-9999 over
        # step-10000 once past the 4-digit zero padding
        steps = sorted((d for d in os.listdir(ckpt_dir)
                        if d.startswith("step-") and step_no(d) is not None),
                       key=step_no, reverse=True)
    except OSError:
        return None
    for d in steps:
        full = os.path.join(ckpt_dir, d)
        if all(os.path.exists(os.path.join(full, f"meta-proc{p}.json"))
               and os.path.exists(os.path.join(full, f"shards-proc{p}.npz"))
               for p in range(n_procs)):
            return step_no(d)
    return None


def _read_meta(ckpt_dir):
    metas = sorted(f for f in os.listdir(ckpt_dir)
                   if f.startswith("meta-proc") and f.endswith(".json"))
    if not metas:
        raise MXNetError(f"no sharded checkpoint found in {ckpt_dir!r}")
    merged = {}
    for fname in metas:
        with open(os.path.join(ckpt_dir, fname)) as f:
            part = json.load(f)
        for key, info in part.items():
            if key in merged:
                merged[key]["shards"].extend(info["shards"])
            else:
                merged[key] = info
    return merged


class _ShardReader:
    """Lazily-opened per-process npz files with bf16 untagging."""

    def __init__(self, ckpt_dir):
        self.dir = ckpt_dir
        self._files = {}
        self._cache = {}

    def get(self, proc, entry):
        # memoized: replicated arrays request the same entry once per
        # local device, and target shards can straddle saved pieces
        cached = self._cache.get((proc, entry))
        if cached is not None:
            return cached
        npz = self._files.get(proc)
        if npz is None:
            npz = np.load(os.path.join(self.dir, f"shards-proc{proc}.npz"))
            self._files[proc] = npz
        if "__bf16__:" + entry in npz.files:
            data = npz["__bf16__:" + entry].view(np_dtype("bfloat16"))
        else:
            data = npz[entry]
        self._cache[(proc, entry)] = data
        return data


def load_sharded(ckpt_dir, target):
    """Restore a checkpoint written by :func:`save_sharded` into the
    layout of ``target`` (a pytree of jax.Arrays whose shardings define
    where each piece should live — typically the live trainer state).

    Returns ``(new_tree, extra)`` where ``new_tree`` mirrors ``target``
    with restored values and ``extra`` is the saved host-side dict (or
    ``None``).  Saved and target layouts may differ: each target shard
    is assembled from every saved piece that intersects it.
    """
    meta = _read_meta(ckpt_dir)
    reader = _ShardReader(ckpt_dir)

    flat, treedef = jax.tree_util.tree_flatten_with_path(target)
    new_leaves = []
    for path, cur in flat:
        key = jax.tree_util.keystr(path)
        info = meta.get(key)
        if info is None:
            raise MXNetError(
                f"checkpoint {ckpt_dir!r} has no entry for {key!r}")
        shape = tuple(info["shape"])
        if shape != tuple(np.shape(cur)):
            raise MXNetError(
                f"shape mismatch for {key!r}: checkpoint {shape} vs "
                f"live {tuple(np.shape(cur))}")
        dtype = np_dtype(info["dtype"])
        # the live layout is the authority on dtype (a trainer built
        # with dtype='bfloat16' must not silently come back f32)
        target_dtype = getattr(cur, "dtype", None) or dtype
        shards = info["shards"]

        def make(index, *, _shards=shards, _shape=shape, _dtype=dtype,
                 _target_dtype=target_dtype, _key=key):
            bounds = _norm_index(index, _shape)
            out_shape = tuple(b[1] - b[0] for b in bounds)
            out = np.empty(out_shape, _dtype)
            filled = 0
            for sh in _shards:
                src_b = sh["index"]
                inter = [(max(a0, b0), min(a1, b1))
                         for (a0, a1), (b0, b1) in zip(bounds, src_b)]
                if any(lo >= hi for lo, hi in inter):
                    continue
                data = reader.get(sh["proc"], sh["entry"])
                src_sel = tuple(slice(lo - b0, hi - b0)
                                for (lo, hi), (b0, _) in zip(inter, src_b))
                dst_sel = tuple(slice(lo - a0, hi - a0)
                                for (lo, hi), (a0, _) in zip(inter, bounds))
                out[dst_sel] = data[src_sel]
                filled += int(np.prod([hi - lo for lo, hi in inter]))
            if filled < int(np.prod(out_shape)):
                raise MXNetError(
                    f"checkpoint shards for {_key!r} do not cover the "
                    "requested region (torn or partial save?)")
            if np_dtype(_target_dtype) != _dtype:
                out = out.astype(np_dtype(_target_dtype))
            return out

        sharding = cur.sharding if hasattr(cur, "sharding") else None
        if sharding is None:
            new_leaves.append(jax.numpy.asarray(make(
                tuple(slice(0, d) for d in shape))))
        else:
            new_leaves.append(jax.make_array_from_callback(
                shape, sharding, make))
    extra = None
    extra_path = os.path.join(ckpt_dir, "extra.json")
    if os.path.exists(extra_path):
        with open(extra_path) as f:
            extra = json.load(f)
    return jax.tree_util.tree_unflatten(treedef, new_leaves), extra
