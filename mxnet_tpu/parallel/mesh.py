"""Device mesh management.

The TPU-native replacement for the reference's device-list plumbing
(ctx lists in Module, kvstore device groups): a named ``jax.sharding.Mesh``
over the chip grid, with axes for data (dp), tensor (tp), pipeline (pp),
sequence (sp) and expert (ep) parallelism.  Collectives ride ICI within a
slice and DCN across slices — XLA chooses based on mesh topology.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "Mesh", "NamedSharding", "PartitionSpec", "replicated",
           "shard_along", "local_mesh"]


def make_mesh(axes, devices=None) -> Mesh:
    """Create a Mesh from an ordered {axis_name: size} dict.

    A size of -1 absorbs the remaining devices (like a reshape wildcard)::

        mesh = make_mesh({"dp": -1, "tp": 2})
    """
    if devices is None:
        devices = jax.devices()
    names = list(axes)
    sizes = [axes[n] for n in names]
    n_dev = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n_dev % known:
            raise ValueError(f"cannot infer axis: {n_dev} devices, known {known}")
        sizes[sizes.index(-1)] = n_dev // known
    total = int(np.prod(sizes))
    if total > n_dev:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {n_dev}")
    grid = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(grid, names)


def local_mesh(axis_name="dp") -> Mesh:
    """1-D mesh over all local devices."""
    return make_mesh({axis_name: -1})


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_along(mesh: Mesh, axis_name, dim=0) -> NamedSharding:
    """Sharding that splits array dimension ``dim`` along mesh axis."""
    spec = [None] * dim + [axis_name]
    return NamedSharding(mesh, PartitionSpec(*spec))
