"""Device mesh management.

The TPU-native replacement for the reference's device-list plumbing
(ctx lists in Module, kvstore device groups): a named ``jax.sharding.Mesh``
over the chip grid, with axes for data (dp), tensor (tp), pipeline (pp),
sequence (sp) and expert (ep) parallelism.  Collectives ride ICI within a
slice and DCN across slices — XLA chooses based on mesh topology.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["make_mesh", "make_hybrid_mesh", "Mesh", "NamedSharding",
           "PartitionSpec", "replicated", "shard_along", "local_mesh"]


def make_mesh(axes, devices=None) -> Mesh:
    """Create a Mesh from an ordered {axis_name: size} dict.

    A size of -1 absorbs the remaining devices (like a reshape wildcard)::

        mesh = make_mesh({"dp": -1, "tp": 2})
    """
    if devices is None:
        devices = jax.devices()
    names = list(axes)
    sizes = [axes[n] for n in names]
    n_dev = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n_dev % known:
            raise ValueError(f"cannot infer axis: {n_dev} devices, known {known}")
        sizes[sizes.index(-1)] = n_dev // known
    total = int(np.prod(sizes))
    if total > n_dev:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, "
                         f"have {n_dev}")
    grid = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(grid, names)


def _slice_groups(devices, n_slices=None):
    """Group ``devices`` by TPU slice, one list per slice.

    Multi-slice TPU runtimes expose ``slice_index`` on each device; when
    present it is authoritative (and ``n_slices``, if also given, is
    cross-checked).  CPU/test devices carry no slice attribute, so the
    caller must say how many slices to emulate and the devices are split
    into that many contiguous blocks — the same order a slice-major
    ``jax.devices()`` enumeration would produce on real hardware.
    """
    ids = [getattr(d, "slice_index", None) for d in devices]
    if any(i is not None for i in ids):
        if any(i is None for i in ids):
            raise ValueError("mixed device list: some devices carry "
                             "slice_index and some do not — filter to one "
                             "device kind before building a hybrid mesh")
        by_slice = {}
        for d, i in zip(devices, ids):
            by_slice.setdefault(int(i), []).append(d)
        groups = [sorted(g, key=lambda d: d.id)
                  for _, g in sorted(by_slice.items())]
        if n_slices is not None and len(groups) != n_slices:
            raise ValueError(f"devices span {len(groups)} slices, "
                             f"caller expected {n_slices}")
    else:
        if n_slices is None:
            raise ValueError("devices carry no slice_index attribute; "
                             "pass the dcn axis sizes concretely (they "
                             "define the slice count)")
        if len(devices) % n_slices:
            raise ValueError(f"{len(devices)} devices do not split into "
                             f"{n_slices} equal slices")
        per = len(devices) // n_slices
        groups = [list(devices[i * per:(i + 1) * per])
                  for i in range(n_slices)]
    if len({len(g) for g in groups}) != 1:
        raise ValueError("uneven slice sizes: "
                         f"{[len(g) for g in groups]}")
    return groups


def make_hybrid_mesh(dcn_axes, ici_axes, devices=None) -> Mesh:
    """Mesh over a multi-slice topology: DCN axes outermost.

    ``dcn_axes`` span slices (joined only by DCN), ``ici_axes`` span the
    chips within each slice (joined by ICI).  This encodes the
    slow-axis-outermost rule (docs/how_to/cloud.md): axes whose
    collectives are small and latency-tolerant (dp gradient psums) cross
    slices, while bandwidth-hungry axes (tp all-gathers, sp ring
    permutes) stay inside one slice::

        # 2 slices x 4 chips: dp crosses DCN, tp rides ICI
        mesh = make_hybrid_mesh({"dp": 2}, {"tp": 4})

    Devices are grouped by their ``slice_index`` attribute (real
    multi-slice TPU); CPU/test devices fall back to contiguous blocks,
    so the dryrun can validate the layout on a virtual mesh.  A size of
    -1 in ``ici_axes`` absorbs the rest of a slice; DCN sizes must be
    concrete (their product defines the slice count when the runtime
    doesn't).
    """
    if devices is None:
        devices = jax.devices()
    dcn_names, ici_names = list(dcn_axes), list(ici_axes)
    dcn_sizes = [dcn_axes[n] for n in dcn_names]
    if any(s == -1 for s in dcn_sizes):
        raise ValueError("dcn axis sizes must be concrete (-1 is only "
                         "supported on ici axes)")
    n_slices = int(np.prod(dcn_sizes)) if dcn_sizes else 1
    groups = _slice_groups(devices, n_slices=n_slices)
    per_slice = len(groups[0])
    ici_sizes = [ici_axes[n] for n in ici_names]
    if -1 in ici_sizes:
        known = int(np.prod([s for s in ici_sizes if s != -1]))
        if per_slice % known:
            raise ValueError(f"cannot infer ici axis: {per_slice} "
                             f"chips/slice, known {known}")
        ici_sizes[ici_sizes.index(-1)] = per_slice // known
    ici_total = int(np.prod(ici_sizes)) if ici_sizes else 1
    if ici_total != per_slice:
        # strict: an undersized ici spec would silently idle chips in
        # every slice (use -1 to absorb a slice's remainder explicitly)
        raise ValueError(f"ici axes {dict(zip(ici_names, ici_sizes))} need "
                         f"{ici_total} chips/slice, have {per_slice}"
                         + ("" if ici_total > per_slice else
                            " (use -1 to absorb the remainder)"))
    # real multi-slice hardware (slice_index present): let mesh_utils
    # order each slice's sub-grid by physical torus coordinates, so
    # with 2+ ICI axes collectives land on neighbor chips instead of
    # the id-sorted order (which interleaves across the torus).  The
    # contiguous-block reshape remains the virtual-device fallback —
    # CPU/test devices have no topology to order by.
    real_slices = all(getattr(d, "slice_index", None) is not None
                      for d in devices)
    grid = np.empty((n_slices,) + tuple(ici_sizes), dtype=object)
    for i, g in enumerate(groups):
        sub = None
        if real_slices and ici_sizes:
            try:
                from jax.experimental import mesh_utils

                sub = np.asarray(mesh_utils.create_device_mesh(
                    tuple(ici_sizes), devices=g[:ici_total]))
            except Exception:
                sub = None             # no topology info: fall back
        if sub is None:
            sub = np.asarray(g[:ici_total],
                             dtype=object).reshape(ici_sizes)
        grid[i] = sub
    grid = grid.reshape(dcn_sizes + ici_sizes)
    return Mesh(grid, dcn_names + ici_names)


def local_mesh(axis_name="dp") -> Mesh:
    """1-D mesh over all local devices."""
    return make_mesh({axis_name: -1})


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def shard_along(mesh: Mesh, axis_name, dim=0) -> NamedSharding:
    """Sharding that splits array dimension ``dim`` along mesh axis."""
    spec = [None] * dim + [axis_name]
    return NamedSharding(mesh, PartitionSpec(*spec))
