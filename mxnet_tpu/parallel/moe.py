"""Mixture-of-Experts with expert parallelism (``ep`` mesh axis).

New first-class TPU capability (absent in the reference — SURVEY.md §2.4
marks expert parallelism "No").  Implements Switch/top-k token routing
with capacity-based dispatch: each device on the ``ep`` axis owns
``E / n_shards`` experts; tokens are routed with an in-program
``lax.all_to_all`` over ICI (dispatch), run through the local experts,
and routed back (combine), all inside one ``shard_map``-compiled XLA
program so the router, both all-to-alls, the expert FFNs, and the
load-balancing auxiliary loss fuse into a single differentiable step.

Dispatch math follows the standard capacity formulation (Switch
Transformer / GShard): per-expert capacity ``C = ceil(k * tokens_per
_shard / E * capacity_factor)``; tokens beyond capacity are dropped from
that expert (their combine weight is zero, so the layer degrades to the
residual path if the caller adds one).

Exposed as:
- ``moe_apply(...)`` — functional sharded call (differentiable);
- ``moe_reference(...)`` — identical math, single device, for tests;
- ``MoELayer`` — stateful convenience wrapper (init + trainable step).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from ..jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["moe_apply", "moe_reference", "MoELayer", "init_moe_params"]


def _router(x, gate_w, num_experts, k, capacity):
    """Token routing: returns (dispatch, combine, aux_loss).

    x: (T, D) tokens.  dispatch: (T, E, C) one-hot routing tensor;
    combine: same shape scaled by gate probabilities.
    """
    T = x.shape[0]
    # all routing math in float32: a bf16 cumsum is inexact past 256 and
    # would silently assign duplicate capacity slots
    logits = (x.astype(jnp.float32) @ gate_w.astype(jnp.float32))  # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)

    dispatch = jnp.zeros((T, num_experts, capacity), jnp.float32)
    combine = jnp.zeros((T, num_experts, capacity), jnp.float32)
    masked = probs
    # occupancy per expert carried across the k routing rounds
    occupancy = jnp.zeros((num_experts,), jnp.int32)
    frac_routed = jnp.zeros((num_experts,), jnp.float32)
    for _ in range(k):
        idx = jnp.argmax(masked, axis=-1)                 # (T,)
        gate = jnp.take_along_axis(probs, idx[:, None], axis=-1)[:, 0]
        onehot = jax.nn.one_hot(idx, num_experts, dtype=jnp.float32)  # (T, E)
        # position of each token within its expert's buffer this round
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) + occupancy[None, :].astype(
            jnp.float32)
        pos_int = pos.astype(jnp.int32)
        keep = (pos_int < capacity).astype(jnp.float32) * onehot
        slot = jax.nn.one_hot(pos_int, capacity, dtype=jnp.float32)  # (T,E,C)
        d = keep[..., None] * slot
        dispatch = dispatch + d
        combine = combine + d * gate[:, None, None]
        frac_routed = frac_routed + jnp.sum(onehot, axis=0) / T
        occupancy = occupancy + jnp.sum(keep, axis=0).astype(jnp.int32)
        masked = masked * (1.0 - onehot)                  # exclude chosen

    # Switch-style load-balancing loss: E * <frac tokens> . <mean prob>
    mean_prob = jnp.mean(probs, axis=0)
    aux_loss = num_experts * jnp.sum((frac_routed / k) * mean_prob)
    return dispatch.astype(x.dtype), combine.astype(x.dtype), aux_loss


def _expert_ffn(params_i, h):
    """One expert: two-layer FFN with ReLU (params: w1, b1, w2, b2)."""
    h = jnp.maximum(h @ params_i["w1"] + params_i["b1"], 0.0)
    return h @ params_i["w2"] + params_i["b2"]


def capacity_for(tokens_per_shard, num_experts, k=1, capacity_factor=1.25):
    return max(1, int(math.ceil(k * tokens_per_shard / num_experts
                                * capacity_factor)))


@functools.lru_cache(maxsize=64)
def _build_moe_run(mesh: Mesh, axis: str, k: int, E: int, C: int, expert_fn,
                   batch_axis=None):
    """Cached compiled MoE step for one (mesh, routing config) combo.

    jax.jit caches on function identity + input shapes, so the shard_map
    program must be built once per config, not per call — otherwise every
    training step recompiles.

    ``batch_axis``: optional data-parallel mesh axis the token dim is
    ALSO sharded over.  Each dp replica routes its own tokens among its
    ep group (the all-to-alls stay inside the ep axis, riding ICI), so
    expert parallelism composes with data parallelism in one mesh.
    """
    n_shards = mesh.shape[axis]
    epl = E // n_shards            # experts per shard
    tok_dims = (batch_axis, axis) if batch_axis else axis
    tok_spec = PartitionSpec(tok_dims, None)
    gate_spec = PartitionSpec(None, None)

    def shard_fn(gate_w, experts_local, x_local):
        dispatch, combine, aux = _router(x_local, gate_w, E, k, C)
        # gather each expert's token buffer: (E, C, D)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x_local)
        D = expert_in.shape[-1]
        # dispatch all-to-all: device g receives, from every shard s, the
        # buffers for its expert group -> (n_shards, epl, C, D)
        expert_in = expert_in.reshape(n_shards, epl, C, D)
        expert_in = lax.all_to_all(expert_in, axis, split_axis=0,
                                   concat_axis=0, tiled=False)
        # run local experts over all shards' tokens at once
        flat_in = expert_in.transpose(1, 0, 2, 3).reshape(epl, n_shards * C, D)
        flat_out = jax.vmap(expert_fn)(experts_local, flat_in)
        Do = flat_out.shape[-1]
        # combine all-to-all: route results back to their source shards
        out = flat_out.reshape(epl, n_shards, C, Do).transpose(1, 0, 2, 3)
        out = lax.all_to_all(out, axis, split_axis=0, concat_axis=0,
                             tiled=False)
        out = out.reshape(E, C, Do)
        y_local = jnp.einsum("tec,ecd->td", combine, out)
        # aux loss: average over shards so the global loss is one scalar
        aux = lax.pmean(aux, axis)
        if batch_axis:
            aux = lax.pmean(aux, batch_axis)
        return y_local, aux

    @jax.jit
    def run(gate_w, experts, x):
        exp_spec = jax.tree_util.tree_map(lambda _: PartitionSpec(axis),
                                          experts)
        return shard_map(
            shard_fn, mesh=mesh,
            in_specs=(gate_spec, exp_spec, tok_spec),
            out_specs=(tok_spec, PartitionSpec()),
            check_vma=False)(gate_w, experts, x)

    return run


def moe_apply(params, x, mesh: Mesh, axis: str = "ep", k: int = 1,
              capacity_factor: float = 1.25, expert_fn=_expert_ffn,
              batch_axis=None):
    """Expert-parallel MoE layer over mesh axis ``axis``.

    Parameters
    ----------
    params : dict with "gate_w" (D, E) replicated and "experts", a pytree
        whose leaves have leading dim E (sharded over ``axis``).
    x : (tokens, D) global batch of tokens, sharded over ``axis`` on dim 0
        (replicated input is placed here).
    expert_fn : must be a stable function object — compiled programs are
        cached per (mesh, routing config, expert_fn); a fresh lambda per
        call recompiles and churns the cache.
    batch_axis : optional dp mesh axis the token dim is additionally
        sharded over (dp-major ordering); expert params stay replicated
        across it and each dp replica's ep group routes independently.
        Per-shard capacity then uses tokens / (dp * ep).
    Returns (y, aux_loss) with y sharded like x.
    """
    n_shards = mesh.shape[axis]
    E = params["gate_w"].shape[1]
    if E % n_shards:
        raise ValueError(f"num_experts {E} not divisible by ep={n_shards}")
    if batch_axis is not None:
        if batch_axis == axis:
            raise ValueError(
                f"batch_axis must differ from the expert axis ({axis!r})")
        if batch_axis not in mesh.shape:
            raise ValueError(
                f"batch_axis {batch_axis!r} not in mesh axes "
                f"{tuple(mesh.shape)}")
    n_tok_shards = n_shards * (mesh.shape[batch_axis] if batch_axis else 1)
    T = x.shape[0]
    if T % n_tok_shards:
        raise ValueError(
            f"tokens {T} not divisible by token shards {n_tok_shards}")
    C = capacity_for(T // n_tok_shards, E, k, capacity_factor)
    run = _build_moe_run(mesh, axis, k, E, C, expert_fn, batch_axis)

    if not isinstance(x, jax.core.Tracer):
        tok_dims = (batch_axis, axis) if batch_axis else axis
        x = jax.device_put(x,
                           NamedSharding(mesh, PartitionSpec(tok_dims, None)))
    return run(params["gate_w"], params["experts"], x)


def moe_reference(params, x, n_shards: int, k: int = 1,
                  capacity_factor: float = 1.25, expert_fn=_expert_ffn):
    """Single-device math-identical reference: same per-shard routing and
    capacities as ``moe_apply`` on an ``n_shards``-way mesh."""
    E = params["gate_w"].shape[1]
    T = x.shape[0]
    if T % n_shards:
        raise ValueError(f"tokens {T} not divisible by n_shards={n_shards}")
    C = capacity_for(T // n_shards, E, k, capacity_factor)
    outs, auxes = [], []
    for s in range(n_shards):
        x_local = x[s * (T // n_shards):(s + 1) * (T // n_shards)]
        dispatch, combine, aux = _router(x_local, params["gate_w"], E, k, C)
        expert_in = jnp.einsum("tec,td->ecd", dispatch, x_local)
        expert_out = jax.vmap(expert_fn)(params["experts"], expert_in)
        outs.append(jnp.einsum("tec,ecd->td", combine, expert_out))
        auxes.append(aux)
    return jnp.concatenate(outs, axis=0), jnp.mean(jnp.stack(auxes))


def init_moe_params(rng, d_model, d_hidden, num_experts, d_out=None,
                    dtype=np.float32):
    """Initializer for the default FFN experts + router."""
    d_out = d_model if d_out is None else d_out
    s1 = 1.0 / np.sqrt(d_model)
    s2 = 1.0 / np.sqrt(d_hidden)
    return {
        "gate_w": (rng.standard_normal((d_model, num_experts)) * s1
                   ).astype(dtype),
        "experts": {
            "w1": (rng.standard_normal((num_experts, d_model, d_hidden)) * s1
                   ).astype(dtype),
            "b1": np.zeros((num_experts, d_hidden), dtype),
            "w2": (rng.standard_normal((num_experts, d_hidden, d_out)) * s2
                   ).astype(dtype),
            "b2": np.zeros((num_experts, d_out), dtype),
        },
    }


class MoELayer:
    """Stateful convenience wrapper around ``moe_apply`` (trainable)."""

    def __init__(self, d_model, d_hidden, num_experts, mesh, axis="ep",
                 k=1, capacity_factor=1.25, seed=0, batch_axis=None):
        self.mesh, self.axis, self.k = mesh, axis, k
        self.batch_axis = batch_axis
        self.capacity_factor = capacity_factor
        self.params = init_moe_params(np.random.RandomState(seed), d_model,
                                      d_hidden, num_experts)
        self._steps = {}               # (loss_fn id) -> jitted update

    def __call__(self, x):
        y, aux = moe_apply(self.params, x, self.mesh, self.axis, self.k,
                           self.capacity_factor,
                           batch_axis=self.batch_axis)
        self.last_aux_loss = aux
        return y

    def _make_objective(self, loss_fn, x, aux_weight):
        def objective(params):
            y, aux = moe_apply(params, x, self.mesh, self.axis, self.k,
                               self.capacity_factor,
                               batch_axis=self.batch_axis)
            return loss_fn(y) + aux_weight * aux, aux

        return objective

    def grad_step(self, x, loss_fn, lr=0.01, aux_weight=0.01):
        """One SGD step.  ``loss_fn`` must be a stable function object —
        the jitted update is cached per loss_fn (see
        trainer.cached_sgd_step).  Updates ``last_aux_loss``."""
        from .trainer import cached_sgd_step

        step = cached_sgd_step(self._steps, loss_fn,  # mxtpu-lint: donates=0
                               self._make_objective, has_aux=True)
        loss, self.last_aux_loss, self.params = step(self.params, x, lr,
                                                     aux_weight)
        return loss
