"""Parallelism subsystem: mesh, collectives, sharded training.

First-class TPU capabilities (SURVEY.md §2.4 parallelism inventory):
data parallel (dp), tensor parallel (tp), sequence/context parallel (sp,
ring attention), pipeline parallel (pp), expert parallel (ep, MoE) and
the all-reduce bandwidth benchmark harness.
"""

from .mesh import Mesh, NamedSharding, PartitionSpec, make_mesh, \
    make_hybrid_mesh, local_mesh, replicated, shard_along
from .partition import match_partition_rules, gpt_partition_rules, \
    parse_rules, rules_digest, named_shardings
from .collectives import allreduce, allreduce_bench, psum, all_gather, \
    reduce_scatter, ppermute
from .trainer import ShardedTrainer, sgd_opt, adam_opt, adamw_opt
from .checkpoint import save_sharded, load_sharded
from .ring_attention import ring_attention, attention_reference
from .ulysses import ulysses_attention
from .pipeline import pipeline_apply, PipelineModule
from .moe import moe_apply, moe_reference, MoELayer, init_moe_params

__all__ = ["Mesh", "NamedSharding", "PartitionSpec", "make_mesh", "make_hybrid_mesh", "local_mesh",
           "replicated", "shard_along",
           "match_partition_rules", "gpt_partition_rules", "parse_rules",
           "rules_digest", "named_shardings",
           "allreduce", "allreduce_bench", "psum",
           "all_gather", "reduce_scatter", "ppermute", "ShardedTrainer",
           "sgd_opt", "adam_opt", "adamw_opt",
           "save_sharded", "load_sharded", "ring_attention",
           "attention_reference",
           "ulysses_attention",
           "pipeline_apply", "PipelineModule",
           "moe_apply", "moe_reference", "MoELayer", "init_moe_params"]
