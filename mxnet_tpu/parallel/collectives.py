"""Collective primitives + the ICI all-reduce bandwidth benchmark.

Replacement for the reference's §2.4 communication column (CommCPU tree
reduce, CommDevice P2P all-reduce, ps-lite ZPush/ZPull): on TPU these are
XLA collectives (psum / all_gather / reduce_scatter / ppermute) issued
inside compiled programs over the mesh.  ``allreduce_bench`` is the port
of tools/bandwidth/measure.py — the harness behind BASELINE.md's
"KVStore all-reduce GB/s per device" metric.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec
from ..jax_compat import shard_map

__all__ = ["psum", "all_gather", "reduce_scatter", "ppermute", "allreduce",
           "allreduce_bench"]

# re-exported lax collectives (usable inside shard_map'd functions)
psum = jax.lax.psum
all_gather = jax.lax.all_gather
ppermute = jax.lax.ppermute


def reduce_scatter(x, axis_name):
    return jax.lax.psum_scatter(x, axis_name, tiled=True)


def allreduce(arrays, mesh: Mesh, axis_name="dp"):
    """All-reduce a pytree of per-device-sharded arrays over one mesh axis.

    Equivalent of KVStore push+pull fused: each leaf is stacked on a
    leading device axis; result is the sum, replicated.
    """
    spec = PartitionSpec(axis_name)

    @jax.jit
    def _ar(xs):
        def inner(*leaves):
            return tuple(jax.lax.psum(l, axis_name) for l in leaves)

        flat, treedef = jax.tree_util.tree_flatten(xs)
        out = shard_map(inner, mesh=mesh, in_specs=(spec,) * len(flat),
                        out_specs=(spec,) * len(flat))(*flat)
        return jax.tree_util.tree_unflatten(treedef, out)

    return _ar(arrays)


def _device_loop_s(step, x0, n_iter):
    """Per-iteration seconds of ``step`` with the loop ON DEVICE.

    The chip can sit behind an async remote-dispatch runtime (axon
    tunnel) where every host-side call pays a round trip that dwarfs
    ms-scale device work, so host loops measure dispatch, not compute.
    ``fori_loop`` with a TRACED trip count compiles once and serializes
    iterations through the carried value; the slope between two trip
    counts cancels the constant per-call overhead."""
    run_n = jax.jit(lambda n: jax.lax.fori_loop(0, n, lambda i, c: step(c),
                                                x0))
    jax.block_until_ready(run_n(1))           # compile + warm
    n_lo, n_hi = 2, 2 + n_iter
    tic = time.perf_counter()
    jax.block_until_ready(run_n(n_lo))
    t_lo = time.perf_counter() - tic
    tic = time.perf_counter()
    jax.block_until_ready(run_n(n_hi))
    t_hi = time.perf_counter() - tic
    return max((t_hi - t_lo) / (n_hi - n_lo), 1e-9)


def allreduce_bench(mesh=None, sizes_mb=(1, 4, 16, 64, 256), n_iter=10,
                    dtype=jnp.float32, verbose=True):
    """Measure all-reduce algorithmic bandwidth per device over the mesh.

    Port of tools/bandwidth/measure.py: reports GB/s/device using the
    2(n-1)/n ring all-reduce traffic model on the gradient-sized buffers.
    """
    if mesh is None:
        from .mesh import local_mesh

        mesh = local_mesh("dp")
    axis = mesh.axis_names[0]
    n = mesh.devices.size
    results = []
    for mb in sizes_mb:
        elems = int(mb * 1024 * 1024 / np.dtype(dtype).itemsize)
        sharding = NamedSharding(mesh, PartitionSpec(axis))
        x = jax.device_put(
            jnp.ones((n, elems), dtype), sharding)

        step = lambda v: shard_map(lambda t: jax.lax.psum(t, axis),
                                   mesh=mesh, in_specs=PartitionSpec(axis),
                                   out_specs=PartitionSpec(axis))(v)
        dt = _device_loop_s(step, x, n_iter)
        bytes_moved = 2 * (n - 1) / max(n, 1) * elems * np.dtype(dtype).itemsize
        gbps = bytes_moved / dt / 1e9
        results.append({"size_mb": mb, "time_s": dt, "gbps_per_device": gbps})
        if verbose:
            print(f"allreduce {mb:7.2f} MB over {n} devices: {dt*1e3:8.2f} ms, "
                  f"{gbps:7.2f} GB/s/device")
    return results


def memory_bench(sizes_mb=(64, 256, 1024), n_iter=10, dtype=jnp.float32,
                 verbose=True):
    """Single-device memory-system bandwidth: HBM stream (read+write an
    elementwise op) and host<->device staging transfers.

    The single-chip complement of :func:`allreduce_bench` for the
    bandwidth artifact (reference tools/bandwidth measures PCIe paths the
    same way); on TPU the HBM number should sit near the chip's spec
    (e.g. ~2.7 TB/s on v5p) and staging near PCIe speeds.
    """
    dev = jax.devices()[0]
    results = []
    for mb in sizes_mb:
        elems = int(mb * 1024 * 1024 / np.dtype(dtype).itemsize)
        x = jax.device_put(jnp.ones((elems,), dtype), dev)
        dt = _device_loop_s(lambda v: v + 1, x, n_iter)
        hbm_gbps = 2 * elems * np.dtype(dtype).itemsize / dt / 1e9

        host = np.ones((elems,), np.dtype(dtype))
        tic = time.perf_counter()
        for _ in range(n_iter):
            jax.device_put(host, dev).block_until_ready()
        h2d = elems * host.itemsize * n_iter / (time.perf_counter() - tic) / 1e9
        # On accelerators device_get already materializes host memory; the
        # extra np.array copy is only needed on the CPU backend, where
        # device_get is a zero-copy view that would time as infinite.
        force_copy = dev.platform == "cpu"
        tic = time.perf_counter()
        for _ in range(n_iter):
            out = jax.device_get(x)
            if force_copy:
                np.array(out)
        d2h = elems * host.itemsize * n_iter / (time.perf_counter() - tic) / 1e9
        results.append({"size_mb": mb, "hbm_gbps": hbm_gbps,
                        "h2d_gbps": h2d, "d2h_gbps": d2h})
        if verbose:
            print(f"memory {mb:7.2f} MB: HBM {hbm_gbps:8.1f} GB/s, "
                  f"h2d {h2d:6.2f} GB/s, d2h {d2h:6.2f} GB/s")
    return results
