"""ImageRecordIter: packed-image dataset pipeline.

Rebuild of the reference image pipeline (src/io/iter_image_recordio.cc:472
+ image_aug_default.cc + iter_normalize.h + iter_batchloader.h +
iter_prefetcher.h): RecordIO shards -> multi-threaded JPEG decode +
augmentation -> mean/scale normalize -> batch collation -> background
prefetch.  Distributed sharding via ``part_index``/``num_parts`` (the
dmlc InputSplit role).  Decode threads use OpenCV like the reference's
parser fan-out (iter_image_recordio.cc:150-355).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from . import ndarray as nd
from . import recordio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter

__all__ = ["ImageRecordIter", "ImageAugmenter"]


class ImageAugmenter:
    """Default augmentation chain (reference DefaultImageAugParam,
    src/io/image_aug_default.cc:314): resize, affine
    (rotation + shear + random scale + aspect ratio, with img-size
    clamping), padding, random-size square crop, random/center crop,
    mirror, HSL jitter — same stage order and distributions as the
    reference's Process()."""

    def __init__(self, data_shape, resize=0, rand_crop=False, rand_mirror=False,
                 mirror=False, rotate=-1, max_rotate_angle=0,
                 max_aspect_ratio=0.0, max_shear_ratio=0.0,
                 max_random_scale=1.0, min_random_scale=1.0,
                 max_crop_size=-1, min_crop_size=-1,
                 max_img_size=1e10, min_img_size=0.0, pad=0,
                 random_h=0, random_s=0, random_l=0, fill_value=255,
                 inter_method=1, seed=0):
        self.data_shape = data_shape
        self.resize = resize
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        self.mirror = mirror
        self.rotate = rotate
        self.max_rotate_angle = max_rotate_angle
        self.max_aspect_ratio = max_aspect_ratio
        self.max_shear_ratio = max_shear_ratio
        self.max_random_scale = max_random_scale
        self.min_random_scale = min_random_scale
        self.max_crop_size = max_crop_size
        self.min_crop_size = min_crop_size
        self.max_img_size = max_img_size
        self.min_img_size = min_img_size
        self.pad = pad
        self.random_h = random_h
        self.random_s = random_s
        self.random_l = random_l
        self.fill_value = fill_value

    def _needs_affine(self):
        return (self.rotate >= 0 or self.max_rotate_angle > 0
                or self.max_shear_ratio > 0
                or self.max_random_scale != 1.0
                or self.min_random_scale != 1.0
                or self.max_aspect_ratio != 0.0
                or self.max_img_size != 1e10 or self.min_img_size != 0.0)

    def __call__(self, img, rng):
        import cv2

        fill = (self.fill_value,) * 3
        if self.resize > 0:
            h, w = img.shape[:2]
            if h < w:
                new_h, new_w = self.resize, int(w * self.resize / h)
            else:
                new_h, new_w = int(h * self.resize / w), self.resize
            img = cv2.resize(img, (new_w, new_h))

        # -- affine: rotation + shear + anisotropic random scale --------
        if self._needs_affine():
            shear = (rng.uniform(-self.max_shear_ratio, self.max_shear_ratio)
                     if self.max_shear_ratio > 0 else 0.0)
            if self.rotate >= 0:
                angle = self.rotate
            elif self.max_rotate_angle > 0:
                angle = rng.randint(-self.max_rotate_angle,
                                    self.max_rotate_angle + 1)
            else:
                angle = 0.0
            a = np.cos(np.deg2rad(angle))
            b = np.sin(np.deg2rad(angle))
            scale = rng.uniform(self.min_random_scale, self.max_random_scale)
            ratio = 1.0 + (rng.uniform(-self.max_aspect_ratio,
                                       self.max_aspect_ratio)
                           if self.max_aspect_ratio else 0.0)
            # split the scale between height/width so the AREA scales by
            # scale^2 while w/h changes by `ratio`
            hs = 2.0 * scale / (1.0 + ratio)
            ws = ratio * hs
            h, w = img.shape[:2]
            new_w = int(max(self.min_img_size,
                            min(self.max_img_size, scale * w)))
            new_h = int(max(self.min_img_size,
                            min(self.max_img_size, scale * h)))
            M = np.zeros((2, 3), np.float32)
            M[0, 0] = hs * a - shear * b * ws
            M[1, 0] = -b * ws
            M[0, 1] = hs * b + shear * a * ws
            M[1, 1] = a * ws
            # center the transformed image in the new canvas
            M[0, 2] = (new_w - (M[0, 0] * w + M[0, 1] * h)) / 2.0
            M[1, 2] = (new_h - (M[1, 0] * w + M[1, 1] * h)) / 2.0
            img = cv2.warpAffine(img, M, (max(new_w, 1), max(new_h, 1)),
                                 flags=cv2.INTER_LINEAR,
                                 borderMode=cv2.BORDER_CONSTANT,
                                 borderValue=fill)

        if self.pad > 0:
            img = cv2.copyMakeBorder(img, self.pad, self.pad, self.pad,
                                     self.pad, cv2.BORDER_CONSTANT,
                                     value=fill)

        th, tw = self.data_shape[1], self.data_shape[2]
        h, w = img.shape[:2]
        if self.max_crop_size != -1 or self.min_crop_size != -1:
            # random-size square crop, resized to the target shape; the
            # reference requires both bounds (CHECK max >= min)
            lo, hi = self.min_crop_size, self.max_crop_size
            if lo == -1 or hi == -1 or hi < lo:
                raise MXNetError(
                    "min_crop_size and max_crop_size must both be set "
                    f"with min <= max (got {lo}, {hi})")
            if h < hi or w < hi:
                raise MXNetError("input image smaller than max_crop_size")
            size = rng.randint(lo, hi + 1)
            if self.rand_crop:
                y0 = rng.randint(0, h - size + 1)
                x0 = rng.randint(0, w - size + 1)
            else:
                y0, x0 = (h - size) // 2, (w - size) // 2
            img = cv2.resize(img[y0:y0 + size, x0:x0 + size], (tw, th))
        else:
            if h < th or w < tw:
                img = cv2.resize(img, (max(tw, w), max(th, h)))
                h, w = img.shape[:2]
            if self.rand_crop:
                y0 = rng.randint(0, h - th + 1)
                x0 = rng.randint(0, w - tw + 1)
            else:
                y0, x0 = (h - th) // 2, (w - tw) // 2
            img = img[y0:y0 + th, x0:x0 + tw]

        if self.mirror or (self.rand_mirror and rng.rand() < 0.5):
            img = img[:, ::-1]
        if self.random_h or self.random_s or self.random_l:
            hsl = cv2.cvtColor(img, cv2.COLOR_BGR2HLS).astype(np.float32)
            hsl[..., 0] += rng.uniform(-self.random_h, self.random_h)
            hsl[..., 1] += rng.uniform(-self.random_l, self.random_l)
            hsl[..., 2] += rng.uniform(-self.random_s, self.random_s)
            img = cv2.cvtColor(np.clip(hsl, 0, 255).astype(np.uint8),
                               cv2.COLOR_HLS2BGR)
        return img


class ImageRecordIter(DataIter):
    """Batched iterator over a packed .rec image dataset.

    Composition mirrors the reference registration
    (iter_image_recordio.cc:444-476):
    RecordIO -> [decode+augment thread pool] -> normalize -> batch ->
    prefetch thread.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, part_index=0, num_parts=1,
                 preprocess_threads=4, prefetch_buffer=4,
                 mean_img=None, mean_r=0, mean_g=0, mean_b=0, scale=1.0,
                 rand_crop=False, rand_mirror=False, mirror=False, resize=0,
                 max_rotate_angle=0, random_h=0, random_s=0, random_l=0,
                 data_name="data", label_name="softmax_label", seed=0,
                 round_batch=True, **aug_kwargs):
        super().__init__()
        if not os.path.exists(path_imgrec):
            raise MXNetError(f"record file not found: {path_imgrec}")
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        self.data_name = data_name
        self.label_name = label_name
        self._round_batch = round_batch
        self._rng = np.random.RandomState(seed + part_index)
        self._aug = ImageAugmenter(self.data_shape, resize=resize,
                                   rand_crop=rand_crop,
                                   rand_mirror=rand_mirror, mirror=mirror,
                                   max_rotate_angle=max_rotate_angle,
                                   random_h=random_h, random_s=random_s,
                                   random_l=random_l, **aug_kwargs)
        self._mean = None
        self._mean_img_path = mean_img
        if mean_img is not None and os.path.exists(mean_img):
            self._mean = nd.load(mean_img)["mean_img"].asnumpy()
        elif mean_r or mean_g or mean_b:
            self._mean = np.array([mean_b, mean_g, mean_r],
                                  np.float32).reshape(3, 1, 1)
        self._scale = scale

        # index all record offsets once, then shard (InputSplit role)
        offsets = []
        reader = recordio.MXRecordIO(path_imgrec, "r")
        while True:
            pos = reader.tell()
            if reader.read() is None:
                break
            offsets.append(pos)
        reader.close()
        self._path = path_imgrec
        self._offsets = offsets[part_index::num_parts]
        if not self._offsets:
            raise MXNetError("no records in partition")
        self._threads = preprocess_threads
        self._prefetch = prefetch_buffer
        self._order = None
        self._reset_order()
        if (self._mean_img_path is not None
                and not os.path.exists(self._mean_img_path)):
            self._compute_mean_image(offsets, part_index)
        self._queue = None
        self._producer = None
        self._stop = threading.Event()
        self._start_producer()

    def _compute_mean_image(self, all_offsets, part_index, wait_s=600.0):
        """First-run mean image saved to ``mean_img`` for reuse
        (reference iter_normalize.h: the mean binary is computed on
        first run then loaded thereafter).  Only partition 0 computes —
        over the FULL record set, threaded — and writes atomically;
        other partitions wait for the file to appear so concurrent
        workers neither race the write nor get shard-biased means."""
        marker = self._mean_img_path + ".inprogress"
        if part_index != 0:
            deadline = time.monotonic() + wait_s
            while True:
                if os.path.exists(self._mean_img_path):
                    self._mean = nd.load(
                        self._mean_img_path)["mean_img"].asnumpy()
                    return
                # a fresh in-progress marker means partition 0 shares
                # our filesystem and is still grinding through a large
                # record set — keep waiting past the base deadline
                # rather than N partitions each recomputing the full
                # mean (the marker's mtime is refreshed as it works)
                if time.monotonic() >= deadline:
                    try:
                        # mxtpu-lint: disable=wall-clock (compared
                        # against the marker file's wall-clock mtime)
                        still_working = (time.time()
                                         - os.path.getmtime(marker) < 60.0)
                    except OSError:
                        still_working = False
                    if not still_working:
                        break
                time.sleep(0.2)
            # no shared filesystem with partition 0 (ssh multi-host):
            # compute locally over the full set — duplicate work, same
            # result, no job failure
            import warnings

            warnings.warn(
                f"mean image {self._mean_img_path!r} did not appear in "
                f"{wait_s}s; computing locally (no shared filesystem?)")

        def one(off):
            reader = local.reader
            reader.handle.seek(off)
            raw = reader.read()
            if raw is None:
                return None
            _, img = recordio.unpack_img(raw, iscolor=1)
            img = self._aug(img, np.random.RandomState(0))
            return img.astype(np.float64).transpose(2, 0, 1)

        local = threading.local()
        readers = []

        def one_threaded(off):
            if not hasattr(local, "reader"):
                local.reader = recordio.MXRecordIO(self._path, "r")
                readers.append(local.reader)
            return one(off)

        def touch_marker():
            try:
                with open(marker, "a"):
                    os.utime(marker, None)
            except OSError:
                pass  # best effort; waiters fall back to the deadline

        touch_marker()
        total = np.zeros(self.data_shape, np.float64)
        count = 0
        last_touch = time.monotonic()
        try:
            with ThreadPoolExecutor(max_workers=self._threads,
                                    thread_name_prefix="meanimg") as pool:
                for chw in pool.map(one_threaded, all_offsets):
                    if chw is not None:
                        total += chw
                        count += 1
                    # time-based heartbeat: waiters treat the marker as
                    # stale after 60s, and record decode rate varies too
                    # much for a per-N-records rule (slow NFS can take
                    # minutes per batch of records)
                    if time.monotonic() - last_touch > 5.0:
                        touch_marker()
                        last_touch = time.monotonic()
            for r in readers:
                r.close()
            mean = (total / max(count, 1)).astype(np.float32)
            # pid-unique tmp: partitions that both fell back to local
            # compute must not truncate each other mid-write
            tmp = f"{self._mean_img_path}.tmp.{os.getpid()}"
            nd.save(tmp, {"mean_img": nd.array(mean)})
            os.replace(tmp, self._mean_img_path)
            self._mean = mean
        finally:
            try:
                os.remove(marker)
            except OSError:
                pass

    def _reset_order(self):
        self._order = np.arange(len(self._offsets))
        if self.shuffle:
            self._rng.shuffle(self._order)

    # -- pipeline ----------------------------------------------------------
    def _decode_one(self, raw, rng_seed):
        header, img = recordio.unpack_img(raw, iscolor=1)
        rng = np.random.RandomState(rng_seed)
        img = self._aug(img, rng)
        # HWC BGR uint8 -> CHW float32 (reference keeps BGR order of cv2)
        chw = img.astype(np.float32).transpose(2, 0, 1)
        if self._mean is not None:
            chw = chw - self._mean
        if self._scale != 1.0:
            chw = chw * self._scale
        label = header.label
        if np.isscalar(label):
            label = np.array([label], np.float32)
        return chw, np.asarray(label, np.float32)[:self.label_width]

    def _produce_epoch(self, pool, reader):
        bs = self.batch_size
        n = len(self._order)
        starts = list(range(0, n - bs + 1, bs))
        leftover = n - len(starts) * bs
        if not starts and not (leftover and self._round_batch):
            raise MXNetError("fewer records than batch_size "
                             "(and round_batch disabled)")
        for start in starts:
            yield self._make_batch(pool, reader,
                                   self._order[start:start + bs], pad=0)
        if leftover and self._round_batch:
            # complete the final batch by wrapping to the epoch start and
            # report the pad count (iter_batchloader.h round_batch /
            # num_batch_padd semantics)
            idxs = np.concatenate([self._order[n - leftover:],
                                   np.resize(self._order, bs - leftover)])
            yield self._make_batch(pool, reader, idxs, pad=bs - leftover)

    def _make_batch(self, pool, reader, idxs, pad):
        bs = self.batch_size
        raws = []
        for i in idxs:
            reader.handle.seek(self._offsets[i])
            raws.append(reader.read())
        seeds = self._rng.randint(0, 2**31, size=bs)
        results = list(pool.map(self._decode_one, raws, seeds))
        data = np.stack([r[0] for r in results])
        label = np.stack([r[1] for r in results])
        if self.label_width == 1:
            label = label.reshape(bs)
        return DataBatch([nd.array(data)], [nd.array(label)], pad=pad)

    def _producer_loop(self):
        pool = ThreadPoolExecutor(max_workers=self._threads,
                                  thread_name_prefix="imgdec")
        reader = recordio.MXRecordIO(self._path, "r")
        try:
            while not self._stop.is_set():
                for batch in self._produce_epoch(pool, reader):
                    if self._stop.is_set():
                        return
                    self._queue.put(("batch", batch))
                self._queue.put(("end", None))
                self._reset_order()
        except Exception as e:  # surface to the consumer; never hang it
            self._queue.put(("error", e))
        finally:
            pool.shutdown(wait=False)
            reader.close()

    # -- native fast path (src/image_pipeline.cc) ---------------------------
    def _native_eligible(self):
        """The C++ pipeline covers the standard chain (resize shorter
        side, random/center crop, mirror, mean/scale); rotation and HSL
        jitter stay on the Python path."""
        import os as _os

        if _os.environ.get("MXNET_TPU_NATIVE_IMAGE", "1") == "0":
            return False
        if self._round_batch and len(self._offsets) % self.batch_size:
            # ragged dataset: the wrap-around pad batch (round_batch)
            # is produced by the python chain only
            return False
        a = self._aug
        if (a._needs_affine() or a.pad > 0 or a.max_crop_size != -1
                or a.min_crop_size != -1
                or a.random_h or a.random_s or a.random_l):
            return False
        if self.data_shape[0] not in (1, 3):
            return False
        from .libinfo import find_lib

        lib = find_lib()
        return lib is not None and bool(lib.MXTPUImgPipeAvailable())

    def _producer_loop_native(self):
        import ctypes

        from .base import MXNetError as _Err
        from .libinfo import find_lib

        lib = find_lib()
        c, h, w = self.data_shape
        bs = self.batch_size
        a = self._aug
        mean_rgb = np.zeros(3, np.float32)
        mean_img = None
        if self._mean is not None:
            if self._mean.size == 3:  # per-channel (BGR order, as decoded)
                mean_rgb = np.ascontiguousarray(
                    self._mean.reshape(3), np.float32)
            else:
                mean_img = np.ascontiguousarray(self._mean, np.float32)
                if mean_img.shape != self.data_shape:
                    # the C++ side reads c*h*w floats unchecked; a mean
                    # computed at a different data_shape must fail
                    # loudly like the python broadcast would
                    self._queue.put(("error", _Err(
                        f"mean image shape {mean_img.shape} does not "
                        f"match data_shape {self.data_shape}")))
                    return
        offsets = np.ascontiguousarray(self._offsets, np.int64)
        f32p = ctypes.POINTER(ctypes.c_float)
        i64p = ctypes.POINTER(ctypes.c_int64)
        handle = lib.MXTPUImgPipeCreate(
            self._path.encode(), offsets.ctypes.data_as(i64p), len(offsets),
            bs, c, h, w, self.label_width,
            int(a.resize), int(bool(a.rand_crop)), int(bool(a.rand_mirror)),
            int(bool(a.mirror)), mean_rgb.ctypes.data_as(f32p),
            float(self._scale),
            mean_img.ctypes.data_as(f32p) if mean_img is not None else None,
            self._threads, max(2, self._prefetch),
            int(self._rng.randint(0, 2**62)))
        if not handle:
            # construction failed: fall back to the python chain
            self._producer_loop()
            return
        try:
            while not self._stop.is_set():
                # full batches only, matching the python path
                n_full = (len(self._order) // bs) * bs
                if n_full == 0:
                    raise _Err("fewer records than batch_size")
                epoch = np.ascontiguousarray(
                    offsets[self._order[:n_full]], np.int64)
                lib.MXTPUImgPipeReset(handle, epoch.ctypes.data_as(i64p),
                                      n_full)
                for _ in range(n_full // bs):
                    if self._stop.is_set():
                        return
                    # fresh buffers per batch: queued batches must not
                    # alias memory the next Next() call overwrites
                    # (device_put is async and can be zero-copy on the
                    # CPU backend)
                    data_buf = np.empty((bs, c, h, w), np.float32)
                    label_buf = np.empty((bs, self.label_width), np.float32)
                    r = lib.MXTPUImgPipeNext(
                        handle, data_buf.ctypes.data_as(f32p),
                        label_buf.ctypes.data_as(f32p))
                    if r <= 0:
                        from .c_api import last_error

                        raise _Err(f"native image pipeline: {last_error()}")
                    label = (label_buf.reshape(bs) if self.label_width == 1
                             else label_buf)
                    self._queue.put(("batch", DataBatch(
                        [nd.array(data_buf)], [nd.array(label)], pad=0)))
                self._queue.put(("end", None))
                self._reset_order()
        except Exception as e:  # surface to the consumer; never hang it
            self._queue.put(("error", e))
        finally:
            lib.MXTPUImgPipeDestroy(handle)

    def _start_producer(self):
        self._queue = queue.Queue(maxsize=self._prefetch)
        target = (self._producer_loop_native if self._native_eligible()
                  else self._producer_loop)
        self._producer = threading.Thread(target=target, daemon=True)
        self._producer.start()

    # -- DataIter protocol ---------------------------------------------------
    @property
    def provide_data(self):
        return [DataDesc(self.data_name, (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = ((self.batch_size,) if self.label_width == 1
                 else (self.batch_size, self.label_width))
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        while True:
            kind, payload = self._queue.get()
            if kind == "end":
                return
            if kind == "error":
                raise payload

    def next(self):
        kind, batch = self._queue.get()
        if kind == "end":
            raise StopIteration
        if kind == "error":
            raise batch
        return batch

    def iter_next(self):
        try:
            self._current = self.next()
            return True
        except StopIteration:
            return False

    def __del__(self):
        self._stop.set()
