"""OpenCV bridge (reference plugin/opencv: cv_api.cc + opencv.py).

The reference routed imdecode/resize/copyMakeBorder through its own C++
OpenCV wrappers into NDArrays; here OpenCV's Python bindings do the
pixel work on host and results land in NDArrays — same surface:
``imdecode``, ``resize``, ``copyMakeBorder``, crop/normalize helpers,
and the simple ``ImageListIter`` file-list iterator.

Images are HWC uint8 BGR on host (cv2 convention), converted to
NDArray float32 by the iterator like the reference's pipeline.
"""

from __future__ import annotations

import os
import random as _pyrandom

import numpy as np

from . import io as _io
from . import ndarray as nd
from .base import MXNetError

__all__ = ["imdecode", "resize", "copyMakeBorder", "scale_down",
           "fixed_crop", "random_crop", "color_normalize",
           "random_size_crop", "ImageListIter"]


def _cv2():
    try:
        import cv2
        return cv2
    except ImportError as e:  # pragma: no cover - cv2 is in the image
        raise MXNetError(
            "mxnet_tpu.cv needs the opencv-python package") from e


def imdecode(str_img, flag=1):
    """Decode an encoded image byte string to an HWC uint8 NDArray
    (reference MXCVImdecode)."""
    cv2 = _cv2()
    img = cv2.imdecode(np.frombuffer(str_img, np.uint8), flag)
    if img is None:
        raise MXNetError("imdecode: cannot decode image")
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype=np.uint8)


def resize(src, size, interpolation=None):
    """Resize to ``(w, h)`` (reference MXCVResize).  Dtype preserved —
    cv2 handles uint8 and float natively."""
    cv2 = _cv2()
    interpolation = cv2.INTER_LINEAR if interpolation is None else interpolation
    arr = src.asnumpy()
    out = cv2.resize(arr, tuple(size), interpolation=interpolation)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=arr.dtype)


def copyMakeBorder(src, top, bot, left, right, border_type=None, value=0):
    """Pad an image (reference MXCVcopyMakeBorder).  Dtype preserved."""
    cv2 = _cv2()
    border_type = cv2.BORDER_CONSTANT if border_type is None else border_type
    arr = src.asnumpy()
    out = cv2.copyMakeBorder(arr, top, bot, left, right, border_type,
                             value=value)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=arr.dtype)


def scale_down(src_size, size):
    """Scale ``size`` down to fit in ``src_size`` keeping aspect."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def fixed_crop(src, x0, y0, w, h, size=None, interpolation=None):
    arr = src.asnumpy()
    out = nd.array(arr[y0:y0 + h, x0:x0 + w], dtype=arr.dtype)
    if size is not None and (w, h) != tuple(size):
        out = resize(out, size, interpolation)
    return out


def random_crop(src, size):
    """Random crop to ``(w, h)`` (scaled down if needed); returns
    (cropped, (x0, y0, w, h))."""
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = scale_down((w, h), size)
    x0 = _pyrandom.randint(0, w - new_w)
    y0 = _pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src.astype(np.float32) - nd.array(np.asarray(mean, np.float32))
    if std is not None:
        src = src / nd.array(np.asarray(std, np.float32))
    return src


def random_size_crop(src, size, min_area=0.25, ratio=(3.0 / 4.0, 4.0 / 3.0)):
    """Random area+aspect crop (reference random_size_crop); falls back
    to random_crop when no candidate fits."""
    h, w = src.shape[0], src.shape[1]
    area = w * h
    for _ in range(10):
        new_area = _pyrandom.uniform(min_area, 1.0) * area
        new_ratio = _pyrandom.uniform(*ratio)
        new_w = int(round(np.sqrt(new_area * new_ratio)))
        new_h = int(round(np.sqrt(new_area / new_ratio)))
        if _pyrandom.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = _pyrandom.randint(0, w - new_w)
            y0 = _pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size)
            return out, (x0, y0, new_w, new_h)
    return random_crop(src, size)


class ImageListIter(_io.DataIter):
    """Iterate a file list as batches (reference opencv.py ImageListIter):
    each line of ``flist`` is "<index>\\t<label>\\t<relative path>"."""

    def __init__(self, root, flist, batch_size, size, mean=None):
        super().__init__()
        self.root = root
        self.batch_size = batch_size
        self.size = tuple(size)
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.list = []
        with open(flist) as f:
            for line in f:
                parts = line.strip().split("\t")
                if len(parts) >= 3:
                    self.list.append((float(parts[1]), parts[2]))
        self.cur = 0

    @property
    def provide_data(self):
        return [("data", (self.batch_size, self.size[1], self.size[0], 3))]

    @property
    def provide_label(self):
        return [("softmax_label", (self.batch_size,))]

    def reset(self):
        self.cur = 0

    def next(self):
        if self.cur + self.batch_size > len(self.list):
            raise StopIteration
        cv2 = _cv2()
        # decode/resize stay pure-host (numpy) — only the finished batch
        # is placed on device, like the ImageRecordIter pipeline
        data = np.zeros((self.batch_size, self.size[1], self.size[0], 3),
                        np.float32)
        label = np.zeros((self.batch_size,), np.float32)
        for i in range(self.batch_size):
            lab, path = self.list[self.cur + i]
            with open(os.path.join(self.root, path), "rb") as f:
                img = cv2.imdecode(np.frombuffer(f.read(), np.uint8), 1)
            if img is None:
                raise MXNetError(f"cannot decode image {path!r}")
            img = cv2.resize(img, self.size).astype(np.float32)
            if img.ndim == 2:
                img = img[:, :, None]
            if self.mean is not None:
                img = img - self.mean
            data[i] = img
            label[i] = lab
        self.cur += self.batch_size
        return _io.DataBatch(data=[nd.array(data)], label=[nd.array(label)],
                             pad=0, index=None)
