"""Weight initializers (rebuild of python/mxnet/initializer.py).

Name-pattern driven: an ``Initializer`` is called with (name, NDArray) and
dispatches on the arg-name suffix (weight/bias/gamma/beta/moving_*),
exactly like the reference's ``__call__`` (initializer.py:22-68).
"""

from __future__ import annotations

import numpy as np

from .base import MXNetError
from .registry import Registry

__all__ = ["Initializer", "Uniform", "Normal", "Orthogonal", "Xavier",
           "MSRAPrelu", "Load", "Mixed", "One", "Zero", "Constant", "init"]

_INIT_REGISTRY = Registry("initializer")


class Initializer:
    def __call__(self, name, arr):
        if name.startswith("upsampling"):
            self._init_bilinear(name, arr)
        elif name.endswith("bias"):
            self._init_bias(name, arr)
        elif name.endswith("gamma"):
            self._init_gamma(name, arr)
        elif name.endswith("beta"):
            self._init_beta(name, arr)
        elif name.endswith("weight"):
            self._init_weight(name, arr)
        elif name.endswith("moving_mean") or name.endswith("moving_avg"):
            self._init_zero(name, arr)
        elif name.endswith("moving_var"):
            self._init_one(name, arr)
        elif name.endswith("parameters"):
            # fused-RNN flat parameter vectors ('<name>_parameters').  The
            # reference initializer could not handle these (acknowledged
            # TODO at example/rnn/rnn_cell_demo.py:73-85); small-uniform is
            # the standard LSTM/GRU flat-weight default.
            self._init_parameters(name, arr)
        elif name.endswith("state") or name.endswith("state_cell"):
            self._init_zero(name, arr)  # fused-RNN initial states
        else:
            self._init_default(name, arr)

    def _init_parameters(self, name, arr):
        arr[:] = np.random.uniform(-0.07, 0.07, arr.shape)

    def _init_bilinear(self, name, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_bias(self, name, arr):
        arr[:] = 0.0

    def _init_gamma(self, name, arr):
        arr[:] = 1.0

    def _init_beta(self, name, arr):
        arr[:] = 0.0

    def _init_zero(self, name, arr):
        arr[:] = 0.0

    def _init_one(self, name, arr):
        arr[:] = 1.0

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        raise MXNetError(
            f"Unknown initialization pattern for {name!r}; name an initializer "
            "pattern (weight/bias/gamma/beta) or use Mixed")


@_INIT_REGISTRY.register("uniform")
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        self.scale = scale

    def _init_weight(self, name, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape)


@_INIT_REGISTRY.register("normal")
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        self.sigma = sigma

    def _init_weight(self, name, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape)


@_INIT_REGISTRY.register("orthogonal")
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, name, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape)


@_INIT_REGISTRY.register("xavier")
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale if len(shape) > 1 else shape[0]
        fan_out = shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in,
                  "out": fan_out}[self.factor_type]
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape)
        else:
            arr[:] = np.random.normal(0, scale, shape)


@_INIT_REGISTRY.register("msraprelu")
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        super().__init__("gaussian", factor_type, 2.0 / (1 + slope**2))


@_INIT_REGISTRY.register("zero")
class Zero(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 0.0

    _init_default = _init_weight


@_INIT_REGISTRY.register("one")
class One(Initializer):
    def _init_weight(self, name, arr):
        arr[:] = 1.0

    _init_default = _init_weight


class Constant(Initializer):
    def __init__(self, value):
        self.value = value

    def _init_weight(self, name, arr):
        arr[:] = self.value

    _init_default = _init_weight


class Load:
    """Initialize from saved dict; fall back to ``default_init``
    (initializer.py Load)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if tuple(self.param[name].shape) != tuple(arr.shape):
                raise MXNetError(f"shape mismatch loading {name}")
            arr[:] = self.param[name]
        else:
            if self.default_init is None:
                raise MXNetError(f"cannot init {name}: not found and no default")
            self.default_init(name, arr)


class Mixed:
    """Regex-pattern routed initializers (initializer.py Mixed)."""

    def __init__(self, patterns, initializers):
        import re

        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers must pair up")
        self.map = [(re.compile(p), i) for p, i in zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, ini in self.map:
            if pat.match(name):
                ini(name, arr)
                return
        raise MXNetError(f"no initializer pattern matches {name!r}; add '.*'")


def init(name, **kwargs):
    """Create a registered initializer by name."""
    return _INIT_REGISTRY.get(name)(**kwargs)
