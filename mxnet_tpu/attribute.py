"""Attribute scoping (parity module for python/mxnet/attribute.py).

The implementation lives in mxnet_tpu.symbol; re-exported here so code
written against the reference layout (``mx.attribute.AttrScope``) works.
"""

from .symbol import AttrScope

__all__ = ["AttrScope"]
