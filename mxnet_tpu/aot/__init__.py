"""AOT startup subsystem: make process restart cheap.

Every program this framework runs is traced and XLA-compiled per shape
bucket; without persistence a restart (preemption recovery, rolling
deploy, elastic reshard) pays the whole trace+compile bill again before
serving its first token — even though sharded checkpointing already
makes the *state* side of recovery fast.  This package is the compile
side of that story, in three layers that compose but work alone:

- :mod:`cache` — jax's persistent compilation cache wired behind
  ``MXTPU_COMPILE_CACHE=<dir>`` (auto-enabled at import): XLA compiles
  become disk reads across processes.  Eviction policy, version
  namespacing, ``mxtpu_compile_cache_{hits,misses,puts}`` counters.
- :mod:`export_store` — serialized ``jax.export`` executables behind
  ``MXTPU_AOT_DIR=<dir>``: Python trace+lower of the serve engine's
  bucketed programs and the fused train step becomes a file
  deserialize.  Fingerprint-keyed; stale/corrupt artifacts fall back
  silently to fresh compilation.
- :mod:`warmup` — JSONL manifests of the (kind, bucket) programs live
  traffic actually hit (``MXTPU_WARMUP_MANIFEST=<path>``), replayed by
  ``serve.Engine.warmup()`` before traffic is admitted and pre-baked
  offline by ``tools/aot_warmup.py``.

``tools/startup_bench.py`` measures the result (STARTUP_BENCH.json:
cold vs warm engine-ready time and compile counts); the operational
recipe lives in docs/how_to/startup.md.
"""

from __future__ import annotations

from . import cache, export_store, warmup
from .cache import CompileCacheManager
from .export_store import ExportStore, default_store, digest, fingerprint
from .warmup import ManifestRecorder, load_manifest

__all__ = ["cache", "export_store", "warmup", "CompileCacheManager",
           "ExportStore", "ManifestRecorder", "default_store", "digest",
           "fingerprint", "load_manifest", "enable_from_env"]


def enable_from_env():
    """Apply the env-var wiring (called from ``mxnet_tpu/__init__``):
    ``MXTPU_COMPILE_CACHE`` enables the persistent compile cache.  The
    export store and manifests resolve their env vars lazily at use."""
    return cache.enable_from_env()
