"""Traffic-replay warmup manifests.

A bucketed engine compiles one program per (kind, bucket) it actually
serves — which shapes those are is a property of the *traffic*, not the
config.  The manifest captures it: one JSONL line per distinct program
the engine executed, appended live while serving (opt-in via
``MXTPU_WARMUP_MANIFEST=<path>``)::

  {"kind": "prefill", "bucket": 32}
  {"kind": "decode", "bucket": 4}

A restarted (or pre-baked, ``tools/aot_warmup.py``) process replays it
through ``Engine.warmup(manifest)`` before admitting traffic, so the
first unlucky request never pays a trace+compile.  Lines also carry a
``spec`` digest of the recording engine's program key; replay ignores
entries recorded by an incompatibly-configured engine instead of
compiling programs the new config can never serve.
"""

from __future__ import annotations

import json
import os

__all__ = ["ManifestRecorder", "load_manifest", "ENV_MANIFEST"]

ENV_MANIFEST = "MXTPU_WARMUP_MANIFEST"


class ManifestRecorder:
    """Dedup-and-append recorder for one engine's program hits.

    In-memory always (``entries()`` feeds ``Engine.save_manifest``);
    mirrored to ``path`` as JSONL when one is given.  Append-per-line
    keeps concurrent engines on one file safe — dedup is per recorder,
    replay dedups again on load.
    """

    def __init__(self, spec_digest, path=None):
        self.spec = spec_digest
        self.path = path
        self._seen = {}

    def record(self, kind, bucket):
        key = (str(kind), int(bucket))
        if key in self._seen:
            return False
        entry = {"kind": key[0], "bucket": key[1], "spec": self.spec}
        self._seen[key] = entry
        if self.path:
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(entry) + "\n")
            except OSError:
                self.path = None       # never let recording break serving
        return True

    def entries(self):
        return list(self._seen.values())


def load_manifest(source, spec_digest=None):
    """Normalize a manifest ``source`` into deduped (kind, bucket)
    entries.

    ``source`` may be a path (JSONL file), an iterable of entry dicts
    (e.g. another engine's ``manifest()``), or None — which resolves
    ``MXTPU_WARMUP_MANIFEST`` and yields [] when unset/absent.  Entries
    recorded under a different ``spec`` digest are skipped when the
    caller passes its own (an old manifest must not force-compile
    programs the current engine cannot serve); entries with no spec are
    trusted (hand-written grids).
    """
    if source is None:
        source = os.environ.get(ENV_MANIFEST)
        if not source:
            return []
    if isinstance(source, (str, os.PathLike)):
        try:
            with open(source) as f:
                lines = f.readlines()
        except OSError:
            return []
        raw = []
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                raw.append(json.loads(line))
            except ValueError:
                continue               # torn tail line of a live file
    else:
        raw = list(source)
    out, seen = [], set()
    for e in raw:
        try:
            kind = str(e["kind"])
            bucket = int(e["bucket"])
        except (TypeError, KeyError, ValueError):
            continue
        if (spec_digest is not None and e.get("spec") is not None
                and e["spec"] != spec_digest):
            continue
        if (kind, bucket) in seen:
            continue
        seen.add((kind, bucket))
        out.append({"kind": kind, "bucket": bucket})
    return out
