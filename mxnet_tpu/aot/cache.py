"""Persistent XLA compile-cache manager.

JAX already ships a persistent compilation cache (compiled executables
keyed by HLO module + compile options + jax version + backend
fingerprint, written as ``<name>-<key>-cache`` files with ``-atime``
companions for LRU accounting).  What it does NOT ship is an opinionated
wiring for a serving framework: it is off by default, its 1-second
minimum-compile-time threshold skips exactly the many-small-programs
workload a bucketed serve engine produces, and nothing manages the
directory's growth across deploys.

:class:`CompileCacheManager` owns that policy behind one env var::

  MXTPU_COMPILE_CACHE=/var/cache/mxtpu   # auto-enabled at import

- every program is cached (min-compile-time 0 by default — bucket
  programs are individually small but collectively the whole cold
  start);
- entries land under a ``jax-<version>/`` subdirectory, so a jax
  upgrade starts a fresh namespace and :meth:`prune` can drop the stale
  one wholesale (the backend fingerprint is already inside jax's own
  cache key — two backends share a subdirectory without collisions);
- byte-size eviction is delegated to jax's own LRU file cache
  (``MXTPU_COMPILE_CACHE_MAX_BYTES``); entry-count eviction
  (``MXTPU_COMPILE_CACHE_MAX_ENTRIES``) is enforced here by pruning
  oldest-access-first, covering jax builds without size limits;
- cache traffic is visible as ``mxtpu_compile_cache_{hits,misses,puts}``
  counters (fed by the ``jax.monitoring`` bridge in
  ``telemetry/jaxmon.py``) and :meth:`snapshot_to` writes a
  ``metrics.jsonl``-shaped line that ``tools/metrics_report.py``
  renders directly.
"""

from __future__ import annotations

import json
import os
import time

from ..base import env_int

__all__ = ["CompileCacheManager", "enable", "enable_from_env", "active",
           "ENV_DIR", "ENV_MAX_BYTES", "ENV_MAX_ENTRIES", "ENV_MIN_SECS"]

ENV_DIR = "MXTPU_COMPILE_CACHE"
ENV_MAX_BYTES = "MXTPU_COMPILE_CACHE_MAX_BYTES"
ENV_MAX_ENTRIES = "MXTPU_COMPILE_CACHE_MAX_ENTRIES"
ENV_MIN_SECS = "MXTPU_COMPILE_CACHE_MIN_COMPILE_SECS"

_active = None


def active():
    """The process-wide manager installed by :func:`enable`, or None."""
    return _active


class CompileCacheManager:
    """Wires and polices jax's persistent compilation cache.

    Construction only records the policy; :meth:`enable` applies it to
    the jax config (idempotent, safe before or after backend init).
    """

    def __init__(self, dir, max_bytes=-1, max_entries=0,
                 min_compile_secs=0.0):
        import jax

        self.base_dir = str(dir)
        # jax's own key covers backend + compile options; the version
        # subdir exists so prune() can retire a whole stale namespace
        self.dir = os.path.join(self.base_dir, f"jax-{jax.__version__}")
        self.max_bytes = int(max_bytes)      # -1 = unlimited
        self.max_entries = int(max_entries)  # 0  = unlimited
        self.min_compile_secs = float(min_compile_secs)
        self.enabled = False

    # -- wiring ------------------------------------------------------------
    def enable(self):
        """Point jax's persistent cache at the managed directory."""
        import jax

        os.makedirs(self.dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", self.dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          self.min_compile_secs)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
        except AttributeError:
            pass                       # knob absent on this jax: fine
        if self.max_bytes:
            try:
                jax.config.update("jax_compilation_cache_max_size",
                                  self.max_bytes)
            except AttributeError:
                pass                   # byte eviction then rides prune()
        # jax memoizes its cache-enabled decision at the FIRST compile
        # of the task; enabling after any jit has run (an embedding
        # process, a test suite) would silently never cache without
        # this reset
        try:
            from jax.experimental.compilation_cache import \
                compilation_cache as _jax_cc

            _jax_cc.reset_cache()
        except (ImportError, AttributeError):
            pass                       # nothing compiled yet: no memo
        self.enabled = True
        self.prune()
        # live introspection: /statusz shows cache geometry, on-disk
        # occupancy and the hit/miss/put traffic counters.  A strong
        # ref is deliberate: the active manager is a process singleton
        # (enable() replaces _active AND, via the fixed name here, the
        # provider) — there is no retire-without-replacement path
        from ..telemetry import statusz

        statusz.register("aot.compile_cache", self.statusz)
        return self

    # -- inspection --------------------------------------------------------
    def _entries(self):
        """[(cache_path, atime, bytes)] oldest-access first.  jax writes
        ``-atime`` companion files; fall back to the filesystem mtime
        when one is missing."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in names:
            if not n.endswith("-cache"):
                continue
            path = os.path.join(self.dir, n)
            try:
                size = os.path.getsize(path)
                stamp = os.path.getmtime(path)
            except OSError:
                continue               # raced with jax's own eviction
            atime_file = os.path.join(self.dir, n[:-len("-cache")]
                                      + "-atime")
            try:
                raw = open(atime_file, "rb").read(8)
                if len(raw) == 8:      # u64 nanoseconds since epoch
                    stamp = int.from_bytes(raw, "little") / 1e9
            except OSError:
                pass
            out.append((path, stamp, size))
        out.sort(key=lambda t: t[1])
        return out

    def stats(self):
        entries = self._entries()
        return {"dir": self.dir, "entries": len(entries),
                "bytes": sum(s for _, _, s in entries),
                "max_bytes": self.max_bytes,
                "max_entries": self.max_entries}

    def statusz(self):
        """/statusz provider: on-disk stats plus the
        ``mxtpu_compile_cache_{hits,misses,puts}`` counters collected
        by the jaxmon bridge (zero when telemetry is disabled).  The
        three families are read directly — not via a full registry
        snapshot, which every /statusz render and flight dump would
        pay for all metrics just to extract three values."""
        from .. import telemetry

        out = dict(self.stats(), enabled=self.enabled)
        reg = telemetry.registry()
        for short, help in (("hits", "persistent compile-cache hits"),
                            ("misses", "persistent compile-cache misses"),
                            ("puts", "persistent compile-cache writes")):
            out[short] = reg.counter(f"mxtpu_compile_cache_{short}",
                                     help).labels().value
        return out

    # how long an unused sibling jax-version namespace survives: a
    # rolling deploy / rollback window keeps BOTH versions' caches warm
    # (their files keep getting touched); only a namespace nothing has
    # written or read for this long is truly retired
    STALE_NAMESPACE_DAYS = 14

    # -- eviction ----------------------------------------------------------
    def prune(self):
        """Evict oldest-access-first down to the entry/byte budgets and
        drop ``jax-*`` version namespaces idle for
        :data:`STALE_NAMESPACE_DAYS`.  Returns the number of entries
        removed."""
        removed = 0
        # mxtpu-lint: disable=wall-clock (compared against filesystem
        # atimes, which are wall-clock by definition)
        cutoff = time.time() - self.STALE_NAMESPACE_DAYS * 86400
        try:
            for n in os.listdir(self.base_dir):
                p = os.path.join(self.base_dir, n)
                if (n.startswith("jax-") and os.path.isdir(p)
                        and p != self.dir
                        and self._newest_mtime(p) < cutoff):
                    removed += self._drop_tree(p)
        except OSError:
            pass
        entries = self._entries()
        total = sum(s for _, _, s in entries)
        over_count = (len(entries) - self.max_entries
                      if self.max_entries else 0)
        for path, _, size in entries:
            over_bytes = self.max_bytes > 0 and total > self.max_bytes
            if over_count <= 0 and not over_bytes:
                break
            for victim in (path, path[:-len("-cache")] + "-atime"):
                try:
                    os.remove(victim)
                except OSError:
                    pass
            total -= size
            over_count -= 1
            removed += 1
        return removed

    @staticmethod
    def _newest_mtime(path):
        """Most recent mtime under ``path`` (the dir itself counts —
        an empty namespace still ages out)."""
        newest = 0.0
        try:
            newest = os.path.getmtime(path)
            for root, _, files in os.walk(path):
                for f in files:
                    try:
                        newest = max(newest, os.path.getmtime(
                            os.path.join(root, f)))
                    except OSError:
                        pass
        except OSError:
            pass
        return newest

    @staticmethod
    def _drop_tree(path):
        removed = 0
        for root, dirs, files in os.walk(path, topdown=False):
            for f in files:
                try:
                    os.remove(os.path.join(root, f))
                    removed += 1
                except OSError:
                    pass
            for d in dirs:
                try:
                    os.rmdir(os.path.join(root, d))
                except OSError:
                    pass
        try:
            os.rmdir(path)
        except OSError:
            pass
        return removed

    # -- telemetry snapshot ------------------------------------------------
    def snapshot_to(self, path=None):
        """Append one ``metrics.jsonl``-shaped line (the registry
        snapshot schema ``tools/metrics_report.py`` reads) describing
        the cache: on-disk entry/byte gauges plus the
        ``mxtpu_compile_cache_*`` counters collected so far.  Default
        path: ``<cache dir>/cache_stats.jsonl``."""
        from .. import telemetry

        st = self.stats()
        metrics = {
            "mxtpu_compile_cache_dir_entries": {
                "kind": "gauge", "help": "persistent cache entries on disk",
                "label_names": [],
                "samples": [{"labels": {}, "value": st["entries"]}]},
            "mxtpu_compile_cache_dir_bytes": {
                "kind": "gauge", "help": "persistent cache bytes on disk",
                "label_names": [],
                "samples": [{"labels": {}, "value": st["bytes"]}]},
        }
        snap = telemetry.registry().snapshot()
        for name in ("mxtpu_compile_cache_hits", "mxtpu_compile_cache_misses",
                     "mxtpu_compile_cache_puts"):
            if name in snap:
                metrics[name] = snap[name]
        path = path or os.path.join(self.dir, "cache_stats.jsonl")
        with open(path, "a") as f:
            # mxtpu-lint: disable=wall-clock (JSONL record timestamp)
            f.write(json.dumps({"ts": round(time.time(), 3),
                                "metrics": metrics}) + "\n")
        return path


def enable(dir, **kw):
    """Install and enable a process-wide manager (idempotent per dir)."""
    global _active
    if _active is not None and _active.base_dir == str(dir):
        return _active
    _active = CompileCacheManager(dir, **kw).enable()
    return _active


def enable_from_env():
    """``MXTPU_COMPILE_CACHE=<dir>`` auto-enable hook (package import).
    Returns the manager, or None when the env var is unset."""
    d = os.environ.get(ENV_DIR)
    if not d:
        return None
    min_secs = os.environ.get(ENV_MIN_SECS)
    return enable(
        d,
        max_bytes=env_int(ENV_MAX_BYTES, -1),
        max_entries=env_int(ENV_MAX_ENTRIES, 0),
        min_compile_secs=float(min_secs) if min_secs else 0.0,
    )
