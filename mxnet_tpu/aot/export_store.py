"""Exported-executable store: serialized ``jax.export`` programs on disk.

The persistent compile cache (cache.py) removes the *XLA compile* from a
restart; this store removes the *trace + lower*.  An artifact is one
file holding a JSON fingerprint header plus the serialized StableHLO of
an exported program (the serve engine's bucketed prefill/decode bodies,
the fused train step).  A restarted process that finds a matching
artifact deserializes it and compiles ``Exported.call`` — no Python
re-trace of the model — and that compile in turn hits the persistent
cache, because the cold process executed through the very same wrapped
module it saved.

Staleness is fingerprint-keyed, never versioned by hand: the
fingerprint folds in the artifact format, jax version, backend platform
and the caller's own program key (engine ``_spec_key()`` fields, fused
step shapes).  Any mismatch — moved checkpoint, dtype change, jax
upgrade, truncated file — makes :meth:`load` return None and the caller
traces fresh; a stale artifact can delay a start, never corrupt one.

Layout under ``MXTPU_AOT_DIR``::

  <dir>/<label>-<fp16>.jaxexport     # header \\n blob
  <dir>/manifest.jsonl               # warmup manifest (warmup.py)
"""

from __future__ import annotations

import hashlib
import json
import os

from .. import jax_compat
from .. import telemetry

__all__ = ["ExportStore", "fingerprint", "digest", "default_store",
           "ENV_DIR"]

ENV_DIR = "MXTPU_AOT_DIR"
FORMAT = "mxtpu.aot.v1"

_MAGIC = b"MXTPUAOT"


def fingerprint(**fields):
    """Canonical fingerprint dict for an AOT artifact: caller fields
    plus format/jax-version/backend.  Everything must be JSON-stable —
    tuples arrive as lists, which is fine as long as producers and
    consumers build the dict the same way (they share this helper)."""
    import jax

    fp = {"format": FORMAT, "jax_version": jax.__version__,
          "backend": jax.default_backend()}
    fp.update(fields)
    return fp


def digest(fp):
    """Stable hex digest of a fingerprint dict (artifact file naming,
    manifest ``spec`` stamps)."""
    return hashlib.sha256(
        json.dumps(fp, sort_keys=True, default=str).encode()).hexdigest()


_digest = digest


def _counter(name, help):
    # re-fetched per call (not cached at construction) so stores built
    # before telemetry.enable() still record afterwards
    return telemetry.counter(name, help, ("kind",))


class ExportStore:
    """Directory of fingerprint-keyed serialized executables."""

    def __init__(self, dir):
        self.dir = str(dir)

    def path_for(self, fp, label="program"):
        return os.path.join(self.dir,
                            f"{label}-{_digest(fp)[:16]}.jaxexport")

    # -- write -------------------------------------------------------------
    def save(self, fp, exported, label="program"):
        """Serialize ``exported`` under fingerprint ``fp``; atomic
        rename so a crashed writer cannot leave a torn artifact.
        Returns the path, or None when serialization is unavailable
        (saving is an optimization — never a hard failure)."""
        try:
            blob = jax_compat.serialize_exported(exported)
        except Exception:
            _counter("mxtpu_aot_errors_total",
                     "AOT artifact failures").labels(kind="serialize").inc()
            return None
        os.makedirs(self.dir, exist_ok=True)
        header = json.dumps({"fingerprint": fp}, sort_keys=True).encode()
        path = self.path_for(fp, label)
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as f:
                f.write(_MAGIC + len(header).to_bytes(8, "little"))
                f.write(header)
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass
            _counter("mxtpu_aot_errors_total",
                     "AOT artifact failures").labels(kind="write").inc()
            return None
        _counter("mxtpu_aot_saves_total",
                 "AOT artifacts written").labels(kind=label).inc()
        return path

    # -- read --------------------------------------------------------------
    def load(self, fp, label="program"):
        """Deserialize the artifact for fingerprint ``fp``.  Returns the
        ``Exported`` or None (missing / stale / corrupt — all silent
        fallbacks to fresh compilation, counted separately)."""
        path = self.path_for(fp, label)
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except OSError:
            return None                       # missing: the common miss
        try:
            if raw[:len(_MAGIC)] != _MAGIC:
                raise ValueError("bad magic")
            n = int.from_bytes(raw[len(_MAGIC):len(_MAGIC) + 8], "little")
            header_end = len(_MAGIC) + 8 + n
            header = json.loads(raw[len(_MAGIC) + 8:header_end])
            # digests, not dict equality: the header round-tripped
            # through JSON (tuples are lists now) — digest() already
            # canonicalizes exactly that
            if digest(header.get("fingerprint", {})) != digest(fp):
                # the 16-hex-digit prefix collided or the file was
                # copied across configs: stale, not corrupt
                _counter("mxtpu_aot_errors_total",
                         "AOT artifact failures").labels(kind="stale").inc()
                return None
            exported = jax_compat.deserialize_exported(raw[header_end:])
        except Exception:
            _counter("mxtpu_aot_errors_total",
                     "AOT artifact failures").labels(kind="corrupt").inc()
            return None
        _counter("mxtpu_aot_loads_total",
                 "AOT artifacts loaded").labels(kind=label).inc()
        return exported

    def entries(self):
        """[(path, bytes)] of artifacts currently in the store."""
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in sorted(names):
            if n.endswith(".jaxexport"):
                p = os.path.join(self.dir, n)
                try:
                    out.append((p, os.path.getsize(p)))
                except OSError:
                    pass
        return out


def default_store():
    """The env-configured store (``MXTPU_AOT_DIR``), or None.  Resolved
    per call so tests and late exports can flip the env var."""
    d = os.environ.get(ENV_DIR)
    if not d:
        return None
    if jax_compat.jax_export() is None:
        return None                    # this jax cannot round-trip
    return ExportStore(d)
