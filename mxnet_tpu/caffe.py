"""Caffe interop (rebuild of plugin/caffe, TPU-native).

The reference plugin embeds libcaffe and runs Caffe layers inside the
graph (plugin/caffe/caffe_op-inl.h: ``mx.symbol.CaffeOp(data_0=...,
prototxt='layer{type:"InnerProduct" ...}')`` plus ``CaffeLoss``).  A TPU
build cannot host Caffe's CPU/CUDA layer implementations, so parity is
achieved by *translation* instead of embedding: the prototxt layer
configs are parsed (protobuf text format, no protobuf dependency) and
mapped onto native operators, which then compile through XLA like any
other symbol.  Two surfaces:

- ``CaffeOp(data_0=..., prototxt=...)`` / ``CaffeLoss(...)``: drop-in
  for the plugin API, supporting the layer types the plugin's examples
  use (InnerProduct, Convolution, Pooling, ReLU/TanH/Sigmoid, LRN,
  Dropout, Softmax[WithLoss], Concat, Eltwise, Flatten, BatchNorm).
- ``prototxt_to_symbol(text)``: whole-net importer — reads a train/deploy
  .prototxt and builds the full symbol graph with named parameters.
"""

from __future__ import annotations

import re

from . import symbol as sym
from .base import MXNetError

__all__ = ["parse_prototxt", "prototxt_to_symbol", "CaffeOp", "CaffeLoss",
           "SUPPORTED_LAYERS"]


# -- protobuf text-format parser (subset: messages, repeated fields) --------

_TOKEN = re.compile(r"""
    (?P<brace_open>\{) | (?P<brace_close>\}) |
    (?P<name>[A-Za-z_][A-Za-z0-9_]*) \s* (?P<colon>:)? |
    (?P<string>"(?:[^"\\]|\\.)*") |
    (?P<number>-?\d+\.?\d*(?:[eE][-+]?\d+)?) |
    (?P<comment>\#[^\n]*)
""", re.VERBOSE)


def _tokenize(text):
    pos = 0
    while pos < len(text):
        if text[pos].isspace():
            pos += 1
            continue
        m = _TOKEN.match(text, pos)
        if m is None:
            raise MXNetError(f"prototxt parse error at {text[pos:pos+40]!r}")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        yield m


def parse_prototxt(text: str) -> dict:
    """Parse protobuf text format into a dict; repeated fields become
    lists.  Handles the subset Caffe net definitions use (no extensions,
    no type annotations)."""
    root = {}
    stack = [root]
    pending = None  # field name awaiting a value or a message block
    for tok in _tokenize(text):
        kind = tok.lastgroup
        if kind == "colon":  # 'field:' — the name+colon matched together
            kind = "name"
        if kind == "name" and pending is None:
            pending = tok.group("name")
            # enum values appear as bare names after a 'name:' — handled
            # below because pending is consumed by the colon branch
        elif kind == "brace_open":
            child = {}
            _append(stack[-1], pending, child)
            stack.append(child)
            pending = None
        elif kind == "brace_close":
            if len(stack) == 1:
                raise MXNetError("prototxt: unbalanced braces")
            stack.pop()
            pending = None
        elif kind in ("string", "number", "name"):
            if pending is None:
                raise MXNetError(f"prototxt: stray value {tok.group()!r}")
            if kind == "string":
                v = tok.group("string")[1:-1]
            elif kind == "number":
                s = tok.group("number")
                v = float(s) if ("." in s or "e" in s or "E" in s) else int(s)
            else:  # bare name == enum or bool literal
                s = tok.group("name")
                v = {"true": True, "false": False}.get(s, s)
            _append(stack[-1], pending, v)
            pending = None
    if len(stack) != 1:
        raise MXNetError("prototxt: unbalanced braces at EOF")
    return root


def _append(msg, field, value):
    if field is None:
        raise MXNetError("prototxt: value without a field name")
    if field in msg:
        if not isinstance(msg[field], list):
            msg[field] = [msg[field]]
        msg[field].append(value)
    else:
        msg[field] = value


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# -- layer translation ------------------------------------------------------

def _pair(param, base, default=0):
    """Caffe's kernel/stride/pad fields: either `<base>_size`-style
    single values or `<base>_h`/`<base>_w`."""
    h = param.get(base + "_h")
    w = param.get(base + "_w")
    if h is not None or w is not None:
        return (int(h or default), int(w or default))
    v = param.get(base + "_size", param.get(base, default))
    if isinstance(v, list):
        v = v[0]
    return (int(v), int(v))


def _conv(layer, ins, name):
    p = layer.get("convolution_param", {})
    no_bias = p.get("bias_term") is False
    num_group = int(p.get("group", 1))
    if num_group != 1:
        raise MXNetError(f"caffe layer {name}: grouped convolution "
                         "is not supported by the importer")
    return sym.Convolution(
        ins[0], num_filter=int(p["num_output"]), kernel=_pair(p, "kernel"),
        stride=_pair(p, "stride", 1), pad=_pair(p, "pad", 0),
        no_bias=no_bias, name=name)


def _pool(layer, ins, name):
    p = layer.get("pooling_param", {})
    pool = {0: "max", 1: "avg", "MAX": "max", "AVE": "avg"}.get(
        p.get("pool", "MAX"))
    if pool is None:
        raise MXNetError(f"caffe layer {name}: unsupported pool type "
                         f"{p.get('pool')!r}")
    if p.get("global_pooling") is True:
        return sym.Pooling(ins[0], global_pool=True, kernel=(1, 1),
                           pool_type=pool, name=name)
    return sym.Pooling(
        ins[0], kernel=_pair(p, "kernel"), stride=_pair(p, "stride", 1),
        pad=_pair(p, "pad", 0), pool_type=pool,
        pooling_convention="full",  # caffe uses ceil output sizing
        name=name)


def _inner_product(layer, ins, name):
    p = layer.get("inner_product_param", {})
    no_bias = p.get("bias_term") is False
    return sym.FullyConnected(sym.Flatten(ins[0]),
                              num_hidden=int(p["num_output"]),
                              no_bias=no_bias, name=name)


def _eltwise(layer, ins, name):
    p = layer.get("eltwise_param", {})
    op = {0: "prod", 1: "sum", 2: "max", "PROD": "prod", "SUM": "sum",
          "MAX": "max"}.get(p.get("operation", "SUM"))
    if op == "sum":
        out = ins[0]
        for i in ins[1:]:
            out = out + i
        return out
    if op == "prod":
        out = ins[0]
        for i in ins[1:]:
            out = out * i
        return out
    out = ins[0]
    for i in ins[1:]:
        out = sym._maximum(out, i)
    return out


def _batchnorm(layer, ins, name):
    p = layer.get("batch_norm_param", {})
    # caffe pairs BatchNorm with a following Scale layer for gamma/beta;
    # our BatchNorm op owns gamma/beta, so a Scale right after BatchNorm
    # is folded away by the importer (see prototxt_to_symbol)
    return sym.BatchNorm(ins[0], eps=float(p.get("eps", 1e-5)),
                         momentum=float(p.get("moving_average_fraction",
                                              0.999)),
                         fix_gamma=False, name=name)


def _lrn(layer, ins, name):
    p = layer.get("lrn_param", {})
    return sym.lrn(ins[0], nsize=int(p.get("local_size", 5)),
                   alpha=float(p.get("alpha", 1e-4)),
                   beta=float(p.get("beta", 0.75)),
                   knorm=float(p.get("k", 2.0)), name=name)


SUPPORTED_LAYERS = {
    "Convolution": _conv,
    "Pooling": _pool,
    "InnerProduct": _inner_product,
    "ReLU": lambda l, ins, n: sym.Activation(ins[0], act_type="relu", name=n),
    "TanH": lambda l, ins, n: sym.Activation(ins[0], act_type="tanh", name=n),
    "Sigmoid": lambda l, ins, n: sym.Activation(ins[0], act_type="sigmoid",
                                                name=n),
    "Dropout": lambda l, ins, n: sym.Dropout(
        ins[0], p=float(l.get("dropout_param", {}).get("dropout_ratio", 0.5)),
        name=n),
    "Softmax": lambda l, ins, n: sym.SoftmaxActivation(ins[0], name=n),
    "SoftmaxWithLoss": lambda l, ins, n: sym.SoftmaxOutput(
        ins[0], *ins[1:2], name=n.replace("loss", "softmax") if "loss" in n
        else n),
    "Concat": lambda l, ins, n: sym.Concat(
        *ins, num_args=len(ins),
        dim=int(l.get("concat_param", {}).get("axis", 1)), name=n),
    "Eltwise": _eltwise,
    "Flatten": lambda l, ins, n: sym.Flatten(ins[0], name=n),
    "BatchNorm": _batchnorm,
    "LRN": _lrn,
}

_SKIPPED_LAYERS = ("Accuracy", "Silence")
_INPUT_LAYERS = ("Data", "Input", "ImageData", "MemoryData", "HDF5Data")


def prototxt_to_symbol(text: str, label_name: str = "softmax_label"):
    """Import a Caffe net definition as a native Symbol.

    Data layers become the ``data`` Variable; ``SoftmaxWithLoss`` becomes
    SoftmaxOutput; BatchNorm+Scale pairs are folded (our BatchNorm owns
    gamma/beta); train/test-phase-restricted duplicates prefer the TRAIN
    phase.  Raises on layer types outside ``SUPPORTED_LAYERS``.
    """
    net = parse_prototxt(text)
    layers = _as_list(net.get("layer")) or _as_list(net.get("layers"))
    if not layers:
        raise MXNetError("prototxt has no layers")

    tops = {}  # caffe top name -> symbol
    bn_syms = set()  # id()s of BatchNorm outputs, for Scale folding
    # (Symbol has __slots__, so marker attributes cannot be attached)

    def get_bottom(names):
        outs = []
        for b in names:
            if b in ("label",):
                outs.append(sym.Variable(label_name))
            elif b in tops:
                outs.append(tops[b])
            elif b == "data":
                outs.append(sym.Variable("data"))
            else:
                raise MXNetError(f"caffe import: unknown bottom {b!r}")
        return outs

    last = None
    for layer in layers:
        ltype = layer.get("type")
        name = str(layer.get("name", ltype))
        if isinstance(ltype, int):  # V1 enum ids not supported
            raise MXNetError("caffe import: V1 (enum-typed) prototxt is "
                             "not supported; upgrade with caffe's "
                             "upgrade_net_proto_text tool")
        # phase-restricted layers: keep TRAIN versions, skip TEST dups
        include = _as_list(layer.get("include"))
        if any(i.get("phase") in ("TEST", 1) for i in include if isinstance(i, dict)):
            continue
        bottoms = [str(b) for b in _as_list(layer.get("bottom"))]
        top_names = [str(t) for t in _as_list(layer.get("top"))]
        if ltype in _INPUT_LAYERS:
            for t in top_names:
                if t != "label":
                    tops[t] = sym.Variable("data")
            continue
        if ltype in _SKIPPED_LAYERS:
            continue
        if ltype == "Scale" and bottoms and bottoms[0] in tops and \
                id(tops[bottoms[0]]) in bn_syms:
            # fold Scale into the preceding BatchNorm (gamma/beta are
            # already parameters of our BatchNorm op)
            for t in top_names:
                tops[t] = tops[bottoms[0]]
            continue
        fn = SUPPORTED_LAYERS.get(ltype)
        if fn is None:
            raise MXNetError(
                f"caffe import: unsupported layer type {ltype!r} "
                f"(supported: {sorted(SUPPORTED_LAYERS)})")
        out = fn(layer, get_bottom(bottoms), name)
        if ltype == "BatchNorm":
            bn_syms.add(id(out))
        for t in top_names:
            tops[t] = out
        last = out
    return last


def _single_layer(prototxt):
    net = parse_prototxt(prototxt)
    layer = net.get("layer") or net.get("layers")
    if isinstance(layer, list):
        layer = layer[0]
    if layer is None:
        raise MXNetError(f"CaffeOp: no layer in prototxt {prototxt!r}")
    return layer


def CaffeOp(*args, prototxt="layer{}", num_data=1, num_weight=0, name=None,
            **kwargs):
    """Plugin-API-compatible single-layer op (caffe_op-inl.h).

    Inputs are ``data_0 ... data_{num_data-1}`` (positionally or by
    keyword); the layer config comes from ``prototxt``.  The layer is
    translated to native operators rather than run through libcaffe, so
    it works anywhere the framework does — no Caffe installation.
    """
    ins = list(args)
    for i in range(len(ins), num_data):
        k = f"data_{i}"
        if k not in kwargs:
            break
        ins.append(kwargs.pop(k))
    if not ins:
        raise MXNetError("CaffeOp: no data inputs")
    layer = _single_layer(prototxt)
    ltype = layer.get("type")
    fn = SUPPORTED_LAYERS.get(ltype)
    if fn is None:
        raise MXNetError(f"CaffeOp: unsupported layer type {ltype!r}")
    return fn(layer, ins, name or f"caffe_{ltype.lower()}")


def CaffeLoss(data=None, label=None, grad_scale=1.0, prototxt="layer{}",
              name=None, **kwargs):
    """Plugin-API-compatible loss (caffe_loss-inl.h): SoftmaxWithLoss
    maps to SoftmaxOutput with ``grad_scale``."""
    layer = _single_layer(prototxt)
    ltype = layer.get("type")
    if ltype != "SoftmaxWithLoss":
        raise MXNetError(f"CaffeLoss: unsupported loss type {ltype!r}")
    return sym.SoftmaxOutput(data, label, grad_scale=float(grad_scale),
                             name=name or "caffe_loss")
