"""Caffe interop (rebuild of plugin/caffe, TPU-native).

The reference plugin embeds libcaffe and runs Caffe layers inside the
graph (plugin/caffe/caffe_op-inl.h: ``mx.symbol.CaffeOp(data_0=...,
prototxt='layer{type:"InnerProduct" ...}')`` plus ``CaffeLoss``).  A TPU
build cannot host Caffe's CPU/CUDA layer implementations, so parity is
achieved by *translation* instead of embedding: the prototxt layer
configs are parsed (protobuf text format, no protobuf dependency) and
mapped onto native operators, which then compile through XLA like any
other symbol.  Two surfaces:

- ``CaffeOp(data_0=..., prototxt=...)`` / ``CaffeLoss(...)``: drop-in
  for the plugin API, supporting the layer types the plugin's examples
  use (InnerProduct, Convolution, Pooling, ReLU/TanH/Sigmoid, LRN,
  Dropout, Softmax[WithLoss], Concat, Eltwise, Flatten, BatchNorm).
- ``prototxt_to_symbol(text)``: whole-net importer — reads a train/deploy
  .prototxt and builds the full symbol graph with named parameters.
"""

from __future__ import annotations

import re

from . import symbol as sym
from .base import MXNetError

__all__ = ["parse_prototxt", "prototxt_to_symbol", "CaffeOp", "CaffeLoss",
           "SUPPORTED_LAYERS"]


# -- protobuf text-format parser (subset: messages, repeated fields) --------

_TOKEN = re.compile(r"""
    (?P<brace_open>\{) | (?P<brace_close>\}) |
    (?P<name>[A-Za-z_][A-Za-z0-9_]*) \s* (?P<colon>:)? |
    (?P<string>"(?:[^"\\]|\\.)*") |
    (?P<number>-?\d+\.?\d*(?:[eE][-+]?\d+)?) |
    (?P<comment>\#[^\n]*)
""", re.VERBOSE)


def _tokenize(text):
    pos = 0
    while pos < len(text):
        if text[pos].isspace():
            pos += 1
            continue
        m = _TOKEN.match(text, pos)
        if m is None:
            raise MXNetError(f"prototxt parse error at {text[pos:pos+40]!r}")
        pos = m.end()
        if m.lastgroup == "comment":
            continue
        yield m


def parse_prototxt(text: str) -> dict:
    """Parse protobuf text format into a dict; repeated fields become
    lists.  Handles the subset Caffe net definitions use (no extensions,
    no type annotations)."""
    root = {}
    stack = [root]
    pending = None  # field name awaiting a value or a message block
    for tok in _tokenize(text):
        kind = tok.lastgroup
        if kind == "colon":  # 'field:' — the name+colon matched together
            kind = "name"
        if kind == "name" and pending is None:
            pending = tok.group("name")
            # enum values appear as bare names after a 'name:' — handled
            # below because pending is consumed by the colon branch
        elif kind == "brace_open":
            child = {}
            _append(stack[-1], pending, child)
            stack.append(child)
            pending = None
        elif kind == "brace_close":
            if len(stack) == 1:
                raise MXNetError("prototxt: unbalanced braces")
            stack.pop()
            pending = None
        elif kind in ("string", "number", "name"):
            if pending is None:
                raise MXNetError(f"prototxt: stray value {tok.group()!r}")
            if kind == "string":
                v = tok.group("string")[1:-1]
            elif kind == "number":
                s = tok.group("number")
                v = float(s) if ("." in s or "e" in s or "E" in s) else int(s)
            else:  # bare name == enum or bool literal
                s = tok.group("name")
                v = {"true": True, "false": False}.get(s, s)
            _append(stack[-1], pending, v)
            pending = None
    if len(stack) != 1:
        raise MXNetError("prototxt: unbalanced braces at EOF")
    return root


def _append(msg, field, value):
    if field is None:
        raise MXNetError("prototxt: value without a field name")
    if field in msg:
        if not isinstance(msg[field], list):
            msg[field] = [msg[field]]
        msg[field].append(value)
    else:
        msg[field] = value


def _as_list(v):
    if v is None:
        return []
    return v if isinstance(v, list) else [v]


# -- layer translation ------------------------------------------------------

def _pair(param, base, default=0):
    """Caffe's kernel/stride/pad fields: either `<base>_size`-style
    single values or `<base>_h`/`<base>_w`."""
    h = param.get(base + "_h")
    w = param.get(base + "_w")
    if h is not None or w is not None:
        return (int(h or default), int(w or default))
    v = param.get(base + "_size", param.get(base, default))
    if isinstance(v, list):
        v = v[0]
    return (int(v), int(v))


def _conv(layer, ins, name):
    p = layer.get("convolution_param", {})
    no_bias = p.get("bias_term") is False
    num_group = int(p.get("group", 1))
    if num_group != 1:
        raise MXNetError(f"caffe layer {name}: grouped convolution "
                         "is not supported by the importer")
    return sym.Convolution(
        ins[0], num_filter=int(p["num_output"]), kernel=_pair(p, "kernel"),
        stride=_pair(p, "stride", 1), pad=_pair(p, "pad", 0),
        no_bias=no_bias, name=name)


def _pool(layer, ins, name):
    p = layer.get("pooling_param", {})
    pool = {0: "max", 1: "avg", "MAX": "max", "AVE": "avg"}.get(
        p.get("pool", "MAX"))
    if pool is None:
        raise MXNetError(f"caffe layer {name}: unsupported pool type "
                         f"{p.get('pool')!r}")
    if p.get("global_pooling") is True:
        return sym.Pooling(ins[0], global_pool=True, kernel=(1, 1),
                           pool_type=pool, name=name)
    return sym.Pooling(
        ins[0], kernel=_pair(p, "kernel"), stride=_pair(p, "stride", 1),
        pad=_pair(p, "pad", 0), pool_type=pool,
        pooling_convention="full",  # caffe uses ceil output sizing
        name=name)


def _inner_product(layer, ins, name):
    p = layer.get("inner_product_param", {})
    no_bias = p.get("bias_term") is False
    return sym.FullyConnected(sym.Flatten(ins[0]),
                              num_hidden=int(p["num_output"]),
                              no_bias=no_bias, name=name)


def _eltwise(layer, ins, name):
    p = layer.get("eltwise_param", {})
    op = {0: "prod", 1: "sum", 2: "max", "PROD": "prod", "SUM": "sum",
          "MAX": "max"}.get(p.get("operation", "SUM"))
    if op == "sum":
        out = ins[0]
        for i in ins[1:]:
            out = out + i
        return out
    if op == "prod":
        out = ins[0]
        for i in ins[1:]:
            out = out * i
        return out
    out = ins[0]
    for i in ins[1:]:
        out = sym._maximum(out, i)
    return out


def _batchnorm(layer, ins, name):
    p = layer.get("batch_norm_param", {})
    # caffe pairs BatchNorm with a following Scale layer for gamma/beta;
    # our BatchNorm op owns gamma/beta, so a Scale right after BatchNorm
    # is folded away by the importer (see prototxt_to_symbol)
    return sym.BatchNorm(ins[0], eps=float(p.get("eps", 1e-5)),
                         momentum=float(p.get("moving_average_fraction",
                                              0.999)),
                         fix_gamma=False, name=name)


def _lrn(layer, ins, name):
    p = layer.get("lrn_param", {})
    return sym.lrn(ins[0], nsize=int(p.get("local_size", 5)),
                   alpha=float(p.get("alpha", 1e-4)),
                   beta=float(p.get("beta", 0.75)),
                   knorm=float(p.get("k", 2.0)), name=name)


SUPPORTED_LAYERS = {
    "Convolution": _conv,
    "Pooling": _pool,
    "InnerProduct": _inner_product,
    "ReLU": lambda l, ins, n: sym.Activation(ins[0], act_type="relu", name=n),
    "TanH": lambda l, ins, n: sym.Activation(ins[0], act_type="tanh", name=n),
    "Sigmoid": lambda l, ins, n: sym.Activation(ins[0], act_type="sigmoid",
                                                name=n),
    "Dropout": lambda l, ins, n: sym.Dropout(
        ins[0], p=float(l.get("dropout_param", {}).get("dropout_ratio", 0.5)),
        name=n),
    "Softmax": lambda l, ins, n: sym.SoftmaxActivation(ins[0], name=n),
    "SoftmaxWithLoss": lambda l, ins, n: sym.SoftmaxOutput(
        ins[0], *ins[1:2], name=n.replace("loss", "softmax") if "loss" in n
        else n),
    "Concat": lambda l, ins, n: sym.Concat(
        *ins, num_args=len(ins),
        dim=int(l.get("concat_param", {}).get("axis", 1)), name=n),
    "Eltwise": _eltwise,
    "Flatten": lambda l, ins, n: sym.Flatten(ins[0], name=n),
    "BatchNorm": _batchnorm,
    "LRN": _lrn,
}

_SKIPPED_LAYERS = ("Accuracy", "Silence")
_INPUT_LAYERS = ("Data", "Input", "ImageData", "MemoryData", "HDF5Data")


def prototxt_to_symbol(text: str, label_name: str = "softmax_label"):
    """Import a Caffe net definition as a native Symbol.

    Data layers become the ``data`` Variable; ``SoftmaxWithLoss`` becomes
    SoftmaxOutput; BatchNorm+Scale pairs are folded (our BatchNorm owns
    gamma/beta); train/test-phase-restricted duplicates prefer the TRAIN
    phase.  Raises on layer types outside ``SUPPORTED_LAYERS``.
    """
    net = parse_prototxt(text)
    layers = _as_list(net.get("layer")) or _as_list(net.get("layers"))
    if not layers:
        raise MXNetError("prototxt has no layers")

    tops = {}  # caffe top name -> symbol
    bn_syms = set()  # id()s of BatchNorm outputs, for Scale folding
    # (Symbol has __slots__, so marker attributes cannot be attached)

    def get_bottom(names):
        outs = []
        for b in names:
            if b in ("label",):
                outs.append(sym.Variable(label_name))
            elif b in tops:
                outs.append(tops[b])
            elif b == "data":
                outs.append(sym.Variable("data"))
            else:
                raise MXNetError(f"caffe import: unknown bottom {b!r}")
        return outs

    last = None
    for layer in layers:
        ltype = layer.get("type")
        name = str(layer.get("name", ltype))
        if isinstance(ltype, int):  # V1 enum ids not supported
            raise MXNetError("caffe import: V1 (enum-typed) prototxt is "
                             "not supported; upgrade with caffe's "
                             "upgrade_net_proto_text tool")
        # phase-restricted layers: keep TRAIN versions, skip TEST dups
        include = _as_list(layer.get("include"))
        if any(i.get("phase") in ("TEST", 1) for i in include if isinstance(i, dict)):
            continue
        bottoms = [str(b) for b in _as_list(layer.get("bottom"))]
        top_names = [str(t) for t in _as_list(layer.get("top"))]
        if ltype in _INPUT_LAYERS:
            for t in top_names:
                if t != "label":
                    tops[t] = sym.Variable("data")
            continue
        if ltype in _SKIPPED_LAYERS:
            continue
        if ltype == "Scale" and bottoms and bottoms[0] in tops and \
                id(tops[bottoms[0]]) in bn_syms:
            # fold Scale into the preceding BatchNorm (gamma/beta are
            # already parameters of our BatchNorm op)
            for t in top_names:
                tops[t] = tops[bottoms[0]]
            continue
        fn = SUPPORTED_LAYERS.get(ltype)
        if fn is None:
            raise MXNetError(
                f"caffe import: unsupported layer type {ltype!r} "
                f"(supported: {sorted(SUPPORTED_LAYERS)})")
        out = fn(layer, get_bottom(bottoms), name)
        if ltype == "BatchNorm":
            bn_syms.add(id(out))
        for t in top_names:
            tops[t] = out
        last = out
    return last


def _single_layer(prototxt):
    net = parse_prototxt(prototxt)
    layer = net.get("layer") or net.get("layers")
    if isinstance(layer, list):
        layer = layer[0]
    if layer is None:
        raise MXNetError(f"CaffeOp: no layer in prototxt {prototxt!r}")
    return layer


def CaffeOp(*args, prototxt="layer{}", num_data=1, num_weight=0, name=None,
            **kwargs):
    """Plugin-API-compatible single-layer op (caffe_op-inl.h).

    Inputs are ``data_0 ... data_{num_data-1}`` (positionally or by
    keyword); the layer config comes from ``prototxt``.  The layer is
    translated to native operators rather than run through libcaffe, so
    it works anywhere the framework does — no Caffe installation.
    """
    ins = list(args)
    for i in range(len(ins), num_data):
        k = f"data_{i}"
        if k not in kwargs:
            break
        ins.append(kwargs.pop(k))
    if not ins:
        raise MXNetError("CaffeOp: no data inputs")
    layer = _single_layer(prototxt)
    ltype = layer.get("type")
    fn = SUPPORTED_LAYERS.get(ltype)
    if fn is None:
        raise MXNetError(f"CaffeOp: unsupported layer type {ltype!r}")
    return fn(layer, ins, name or f"caffe_{ltype.lower()}")


def CaffeLoss(data=None, label=None, grad_scale=1.0, prototxt="layer{}",
              name=None, **kwargs):
    """Plugin-API-compatible loss (caffe_loss-inl.h): SoftmaxWithLoss
    maps to SoftmaxOutput with ``grad_scale``."""
    layer = _single_layer(prototxt)
    ltype = layer.get("type")
    if ltype != "SoftmaxWithLoss":
        raise MXNetError(f"CaffeLoss: unsupported loss type {ltype!r}")
    return sym.SoftmaxOutput(data, label, grad_scale=float(grad_scale),
                             name=name or "caffe_loss")


# --------------------------------------------------------------------------
# .caffemodel weight import (tools/caffe_converter parity)
#
# A pure-python protobuf *wire format* reader — no protoc, no caffe, no
# generated bindings.  Field numbers follow the public BVLC caffe.proto:
#   NetParameter: layer=100 (LayerParameter) / layers=2 (V1LayerParameter)
#   LayerParameter: name=1, type=2(str), blobs=7
#   V1LayerParameter: name=4, type=5(enum), blobs=6
#   BlobProto: num=1 channels=2 height=3 width=4 data=5(float,packed)
#              shape=7 (BlobShape: dim=1, int64)
# --------------------------------------------------------------------------
import numpy as _np


def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise MXNetError("caffemodel: truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise MXNetError("caffemodel: malformed varint")


def _wire_fields(buf):
    """Decode one protobuf message into {field_number: [raw values]}.
    Varints come back as ints, length-delimited fields as memoryviews,
    fixed32/64 as raw 4/8-byte memoryviews."""
    fields = {}
    pos, end = 0, len(buf)
    while pos < end:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            if pos + 8 > end:
                raise MXNetError("caffemodel: truncated fixed64 field")
            val = buf[pos:pos + 8]
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > end:
                raise MXNetError(
                    "caffemodel: truncated message (length-delimited "
                    f"field {fnum} wants {ln} bytes, {end - pos} left)")
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            if pos + 4 > end:
                raise MXNetError("caffemodel: truncated fixed32 field")
            val = buf[pos:pos + 4]
            pos += 4
        else:
            raise MXNetError(f"caffemodel: unsupported wire type {wtype}")
        fields.setdefault(fnum, []).append(val)
    return fields


def _floats(raw_list):
    """Repeated float field: packed byte blobs and/or unpacked fixed32
    entries both arrive as byte buffers of multiple-of-4 length."""
    out = []
    for raw in raw_list:
        if isinstance(raw, int):
            raise MXNetError("caffemodel: non-float data field")
        out.append(_np.frombuffer(bytes(raw), dtype="<f4"))
    return _np.concatenate(out) if out else _np.zeros(0, _np.float32)


def _blob_to_array(raw):
    f = _wire_fields(bytes(raw))
    data = _floats(f.get(5, []))
    if not data.size and 8 in f:  # double_data
        data = _np.concatenate(
            [_np.frombuffer(bytes(r), dtype="<f8") for r in f[8]]
        ).astype(_np.float32)
    if 7 in f:  # BlobShape{dim=1}
        sf = _wire_fields(bytes(f[7][0]))
        dims = []
        for r in sf.get(1, []):
            if isinstance(r, int):
                dims.append(r)
            else:  # packed varints
                p = 0
                b = bytes(r)
                while p < len(b):
                    v, p = _read_varint(b, p)
                    dims.append(v)
        shape = tuple(dims)
    else:
        shape = tuple(int(f.get(i, [0])[0]) for i in (1, 2, 3, 4))
        shape = tuple(d for d in shape if d) or (data.size,)
    return data.reshape(shape) if data.size else data


def parse_caffemodel(data: bytes):
    """Parse a serialized NetParameter; returns
    ``[(layer_name, [blob arrays])]`` in file order for every layer that
    carries weights (handles both new ``layer`` and V1 ``layers``)."""
    net = _wire_fields(data)
    out = []
    for fnum, name_f, blob_f in ((100, 1, 7), (2, 4, 6)):
        for raw in net.get(fnum, []):
            f = _wire_fields(bytes(raw))
            if blob_f not in f:
                continue
            name = bytes(f[name_f][0]).decode() if name_f in f else ""
            out.append((name, [_blob_to_array(b) for b in f[blob_f]]))
    return out


def load_caffemodel_params(prototxt_text: str, caffemodel: bytes):
    """Map caffemodel blobs onto this framework's parameter names using
    the prototxt structure (tools/caffe_converter convert_model.py):
    Convolution/InnerProduct -> {name}_weight/_bias; BatchNorm ->
    {name}_moving_mean/_moving_var (scale-factor normalized) with the
    following Scale layer's blobs as {bn_name}_gamma/_beta."""
    net = parse_prototxt(prototxt_text)
    layers = _as_list(net.get("layer")) or _as_list(net.get("layers"))
    ltypes = {str(l.get("name", "")): str(l.get("type", "")) for l in layers}
    # V1 text prototxts write enum-style type names (type: CONVOLUTION);
    # normalize the weight-bearing ones so their blobs are not silently
    # routed to the generic {name}_blob{i} fallback (which convert_model
    # then drops).  Weight-less enum names (RELU, POOLING, ...) and
    # legitimately-uppercase V2 types (ELU) need no mapping — they carry
    # no blobs to lose.
    _v1_weighted = {"CONVOLUTION": "Convolution",
                    "DECONVOLUTION": "Deconvolution",
                    "INNER_PRODUCT": "InnerProduct", "BN": "BatchNorm",
                    "BATCHNORM": "BatchNorm", "SCALE": "Scale"}
    for name, t in list(ltypes.items()):
        if t in _v1_weighted:
            ltypes[name] = _v1_weighted[t]
    # map Scale layers back to the BatchNorm they fold into (same order
    # logic as prototxt_to_symbol: Scale directly consuming a BN top)
    bn_for_scale = {}
    tops_owner = {}
    for l in layers:
        nm = str(l.get("name", ""))
        if str(l.get("type")) == "Scale":
            bots = [str(b) for b in _as_list(l.get("bottom"))]
            if bots and tops_owner.get(bots[0], ("", ""))[1] == "BatchNorm":
                bn_for_scale[nm] = tops_owner[bots[0]][0]
        for t in _as_list(l.get("top")):
            tops_owner[str(t)] = (nm, str(l.get("type")))

    arg_params, aux_params = {}, {}
    for name, blobs in parse_caffemodel(caffemodel):
        ltype = ltypes.get(name, "")
        if ltype in ("Convolution", "Deconvolution", "InnerProduct"):
            if blobs:
                w = blobs[0]
                if ltype == "InnerProduct" and w.ndim == 4:
                    # V1-era blobs carry legacy (1, 1, out, in) shapes;
                    # the FC weight is the trailing 2-d block
                    w = w.reshape(w.shape[-2], w.shape[-1])
                arg_params[f"{name}_weight"] = w
            if len(blobs) > 1:
                arg_params[f"{name}_bias"] = blobs[1].reshape(-1)
        elif ltype == "BatchNorm":
            sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
            sf = 1.0 / sf if sf else 0.0
            aux_params[f"{name}_moving_mean"] = blobs[0].reshape(-1) * sf
            aux_params[f"{name}_moving_var"] = blobs[1].reshape(-1) * sf
            arg_params.setdefault(
                f"{name}_gamma", _np.ones_like(blobs[0].reshape(-1)))
            arg_params.setdefault(
                f"{name}_beta", _np.zeros_like(blobs[0].reshape(-1)))
        elif ltype == "Scale" and name in bn_for_scale:
            bn = bn_for_scale[name]
            arg_params[f"{bn}_gamma"] = blobs[0].reshape(-1)
            if len(blobs) > 1:
                arg_params[f"{bn}_beta"] = blobs[1].reshape(-1)
        elif blobs:
            for i, b in enumerate(blobs):
                arg_params[f"{name}_blob{i}"] = b
    return arg_params, aux_params


def convert_model(prototxt_text: str, caffemodel: bytes,
                  label_name: str = "softmax_label"):
    """Full import: (symbol, arg_params, aux_params) from a Caffe
    deploy/train prototxt + binary caffemodel."""
    from . import ndarray as nd
    symbol = prototxt_to_symbol(prototxt_text, label_name=label_name)
    raw_args, raw_aux = load_caffemodel_params(prototxt_text, caffemodel)
    arg_names = set(symbol.list_arguments())
    aux_names = set(symbol.list_auxiliary_states())
    arg_params = {k: nd.array(v) for k, v in raw_args.items()
                  if k in arg_names}
    aux_params = {k: nd.array(v) for k, v in raw_aux.items()
                  if k in aux_names}
    return symbol, arg_params, aux_params


def load_mean_binaryproto(data: bytes):
    """Decode a Caffe mean-image ``.binaryproto`` (a bare BlobProto)
    into a float32 (c, h, w) array (tools/caffe_converter/mean_image.py).
    Feed the result to ``ImageRecordIter(mean_img=...)`` via
    ``mx.nd.save`` or subtract it manually."""
    arr = _blob_to_array(data)
    arr = _np.asarray(arr, _np.float32)
    if arr.ndim == 4:  # legacy (1, c, h, w)
        arr = arr.reshape(arr.shape[-3:])
    if arr.ndim != 3:
        raise MXNetError(
            f"mean binaryproto decoded to shape {arr.shape}; expected "
            "(c, h, w)")
    return arr
