"""Host storage pool bindings.

Python surface over the native pooled allocator (src/storage.cc — the
rebuild of the reference Storage layer, src/storage/pooled_storage_manager.h).
Device memory is owned by PJRT; this pool serves aligned host staging
buffers (data-pipeline batches, checkpoint IO) where the reference used
pinned cudaMallocHost memory.  Falls back to plain numpy allocation when
the native library is absent.
"""

from __future__ import annotations

import ctypes

import numpy as np

from .libinfo import find_lib

__all__ = ["alloc", "free", "release_all", "stats", "StagingBuffer"]


def _lib():
    return find_lib()


def alloc(size: int):
    """Allocate ``size`` bytes from the pool; returns an int address or
    None without the native library."""
    lib = _lib()
    if lib is None:
        return None
    return lib.MXTPUStorageAlloc(ctypes.c_uint64(size))


def free(ptr, size: int):
    lib = _lib()
    if lib is not None and ptr:
        lib.MXTPUStorageFree(ctypes.c_void_p(ptr), ctypes.c_uint64(size))


def release_all():
    """Drop all pooled buffers (release-on-pressure hook)."""
    lib = _lib()
    if lib is not None:
        lib.MXTPUStorageReleaseAll()


def stats() -> dict:
    lib = _lib()
    if lib is None:
        return {"native": False}
    vals = [ctypes.c_uint64() for _ in range(4)]
    lib.MXTPUStorageStats(*[ctypes.byref(v) for v in vals])
    return {"native": True,
            "allocated_bytes": vals[0].value,
            "pooled_bytes": vals[1].value,
            "alloc_count": vals[2].value,
            "pool_hits": vals[3].value}


class StagingBuffer:
    """A pooled host buffer viewable as a numpy array.

    Usage::

        with StagingBuffer((256, 3, 224, 224), np.float32) as arr:
            arr[...] = batch
            dev = jax.device_put(arr)
    """

    def __init__(self, shape, dtype=np.float32):
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        self.nbytes = int(np.prod(self.shape)) * self.dtype.itemsize
        self._ptr = alloc(self.nbytes)
        if self._ptr:
            buf = (ctypes.c_char * self.nbytes).from_address(self._ptr)
            self.array = np.frombuffer(buf, dtype=self.dtype).reshape(self.shape)
        else:  # fallback: plain numpy
            self.array = np.empty(self.shape, self.dtype)

    def __enter__(self):
        return self.array

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        if self._ptr:
            free(self._ptr, self.nbytes)
            self._ptr = None
            self.array = None
