"""Python side of the flat C API (include/mxtpu/c_api.h).

Publishes the op registry into the native library at import so thin
in-process frontends can discover ops through the C ABI — the rebuild of
the reference's runtime op discovery (MXSymbolListAtomicSymbolCreators /
MXSymbolGetAtomicSymbolInfo, src/c_api/c_api.cc; consumed by
python/mxnet/symbol.py:999-1120 to generate functions).  Here the roles
are inverted — Python is the publisher, since op implementations are XLA
emitters — but the discovery surface and its "typed param signature per
op" contract are the same.

Also exposes the per-thread error ring and list/get introspection
helpers (used by tests and any non-Python binding).
"""

from __future__ import annotations

import ctypes

from . import libinfo
from .ops.op import OP_REGISTRY
from .param import _REQUIRED

__all__ = ["publish_registry", "list_ops", "get_op_info", "last_error"]

_PUBLISHED = False


def _sig_of(field):
    """Render a field as a reference-style type string
    ('float, optional, default=0.5' — the strings the C API hands to
    frontends for docstring/kwargs generation)."""
    tname = getattr(field.type, "__name__", str(field.type))
    if tname == "_coerce_bool":
        tname = "boolean"
    parts = [tname]
    if field.enum:
        parts.append("{" + ", ".join(repr(e) for e in field.enum) + "}")
    if field.required or field.default is _REQUIRED:
        parts.append("required")
    else:
        parts.append(f"optional, default={field.default!r}")
    return ", ".join(parts)


def _c_arr(strings):
    arr = (ctypes.c_char_p * max(len(strings), 1))()
    for i, s in enumerate(strings):
        arr[i] = s.encode()
    return arr


def publish_registry(lib=None):
    """Push every registered op's metadata into the native registry.
    No-op when the native library is unavailable."""
    global _PUBLISHED
    lib = lib or libinfo.find_lib()
    if lib is None:
        return False
    for key in sorted(OP_REGISTRY._entries):
        op = OP_REGISTRY.get(key)
        # the registry's keys are lowercase lookup names; publish the
        # canonical display name ("Convolution") for an op's primary
        # key so C consumers discover the names the docs/examples use
        # (alias keys pass through as themselves: "_add", "crop", ...)
        name = _canonical_name(key)
        try:
            params = op.make_params({}) if op.param_cls else None
        except Exception:
            params = None
        try:
            args = list(op.list_arguments(params))
        except Exception:
            args = ["data"]
        doc = (getattr(op, "__doc__", "") or
               getattr(type(op), "__doc__", "") or "").strip()
        fields = list(op.param_cls._fields.values()) if op.param_cls else []
        pnames = [f.name for f in fields]
        ptypes = [_sig_of(f) for f in fields]
        pdocs = [f.doc or "" for f in fields]
        rc = lib.MXTPURegisterOp(
            name.encode(), doc.encode(), _c_arr(args), len(args),
            _c_arr(pnames), _c_arr(ptypes), _c_arr(pdocs), len(pnames))
        if rc != 0:
            raise RuntimeError(last_error(lib))
    _PUBLISHED = True
    return True


def _ensure_published(lib):
    if not _PUBLISHED:
        publish_registry(lib)


def _canonical_name(key):
    """Display form of a registry key: the op's canonical name for its
    primary key, the key itself for aliases (same rule the native
    registry publication applies)."""
    op = OP_REGISTRY.get(key)
    canonical = getattr(op, "name", key)
    return (canonical if isinstance(canonical, str)
            and canonical.lower() == key else key)


def list_ops():
    """Op names via the C ABI (MXSymbolListAtomicSymbolCreators shape)."""
    lib = libinfo.find_lib()
    if lib is None:
        # same canonical-name contract as the native path
        return sorted(_canonical_name(k) for k in OP_REGISTRY._entries)
    _ensure_published(lib)
    n = ctypes.c_int()
    names = ctypes.POINTER(ctypes.c_char_p)()
    if lib.MXTPUListOps(ctypes.byref(n), ctypes.byref(names)) != 0:
        raise RuntimeError(last_error(lib))
    return [names[i].decode() for i in range(n.value)]


def get_op_info(name):
    """(doc, arg_names, {param: (type_str, doc)}) via the C ABI."""
    lib = libinfo.find_lib()
    if lib is None:
        raise RuntimeError("native library unavailable")
    _ensure_published(lib)
    doc = ctypes.c_char_p()
    n_args = ctypes.c_int()
    n_params = ctypes.c_int()
    arg_names = ctypes.POINTER(ctypes.c_char_p)()
    p_names = ctypes.POINTER(ctypes.c_char_p)()
    p_types = ctypes.POINTER(ctypes.c_char_p)()
    p_docs = ctypes.POINTER(ctypes.c_char_p)()
    rc = lib.MXTPUGetOpInfo(
        name.encode(), ctypes.byref(doc), ctypes.byref(n_args),
        ctypes.byref(arg_names), ctypes.byref(n_params), ctypes.byref(p_names),
        ctypes.byref(p_types), ctypes.byref(p_docs))
    if rc != 0:
        raise KeyError(last_error(lib))
    args = [arg_names[i].decode() for i in range(n_args.value)]
    params = {p_names[i].decode(): (p_types[i].decode(), p_docs[i].decode())
              for i in range(n_params.value)}
    return (doc.value or b"").decode(), args, params


def last_error(lib=None):
    lib = lib or libinfo.find_lib()
    if lib is None:
        return ""
    return (lib.MXTPUGetLastError() or b"").decode()
