"""Native library loading (rebuild of python/mxnet/libinfo.py + base.py's
ctypes loader).

Finds ``libmxtpu.so`` (the C++ runtime: dependency engine, recordio
scanner, storage pool — src/*.cc), building it with make on first use if
a toolchain is available.  All callers degrade gracefully to pure-Python
implementations when the library is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_TRIED = False
_LOCK = threading.Lock()

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_PKG_DIR, "lib", "libmxtpu.so")
_SRC_DIR = os.path.join(os.path.dirname(_PKG_DIR), "src")


def _build():
    if not os.path.isdir(_SRC_DIR):
        return False
    try:
        subprocess.run(["make", "-s", "-j4"], cwd=_SRC_DIR, check=True,
                       capture_output=True, timeout=120)
        return os.path.exists(_LIB_PATH)
    except (subprocess.SubprocessError, OSError):
        return False


def _declare(lib):
    c = ctypes
    lib.MXTPUEngineCreate.restype = c.c_void_p
    lib.MXTPUEngineCreate.argtypes = [c.c_int, c.c_int]
    lib.MXTPUEngineFree.argtypes = [c.c_void_p]
    lib.MXTPUEngineNewVar.restype = c.c_void_p
    lib.MXTPUEngineNewVar.argtypes = [c.c_void_p]
    lib.MXTPUEnginePush.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p, c.POINTER(c.c_void_p), c.c_int,
        c.POINTER(c.c_void_p), c.c_int, c.c_int]
    lib.MXTPUEnginePushPriority.argtypes = [
        c.c_void_p, c.c_void_p, c.c_void_p, c.POINTER(c.c_void_p), c.c_int,
        c.POINTER(c.c_void_p), c.c_int, c.c_int, c.c_int]
    lib.MXTPUEngineWaitForAll.argtypes = [c.c_void_p]
    lib.MXTPUEngineWaitForVar.argtypes = [c.c_void_p, c.c_void_p]
    lib.MXTPUEnginePending.restype = c.c_int64
    lib.MXTPUEnginePending.argtypes = [c.c_void_p]

    lib.MXTPURecordIOIndex.restype = c.c_void_p
    lib.MXTPURecordIOIndex.argtypes = [c.c_char_p, c.POINTER(c.c_int64)]
    lib.MXTPURecordIOIndexGet.argtypes = [c.c_void_p, c.c_int64,
                                          c.POINTER(c.c_uint64),
                                          c.POINTER(c.c_uint32)]
    lib.MXTPURecordIOIndexFree.argtypes = [c.c_void_p]
    lib.MXTPURecordIOReadBatch.restype = c.c_int64
    lib.MXTPURecordIOReadBatch.argtypes = [
        c.c_char_p, c.c_void_p, c.POINTER(c.c_int64), c.c_int64,
        c.POINTER(c.c_uint8), c.c_int64, c.POINTER(c.c_uint32)]

    lib.MXTPUStorageAlloc.restype = c.c_void_p
    lib.MXTPUStorageAlloc.argtypes = [c.c_uint64]
    lib.MXTPUStorageFree.argtypes = [c.c_void_p, c.c_uint64]
    lib.MXTPUStorageReleaseAll.argtypes = []
    lib.MXTPUStorageStats.argtypes = [c.POINTER(c.c_uint64)] * 4

    lib.MXTPUImgPipeAvailable.restype = c.c_int
    lib.MXTPUImgPipeAvailable.argtypes = []
    lib.MXTPUImgPipeCreate.restype = c.c_void_p
    lib.MXTPUImgPipeCreate.argtypes = [
        c.c_char_p, c.POINTER(c.c_int64), c.c_int64,
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.c_int, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_float), c.c_float, c.POINTER(c.c_float),
        c.c_int, c.c_int, c.c_uint64]
    lib.MXTPUImgPipeReset.restype = c.c_int
    lib.MXTPUImgPipeReset.argtypes = [c.c_void_p, c.POINTER(c.c_int64),
                                      c.c_int64]
    lib.MXTPUImgPipeNext.restype = c.c_int
    lib.MXTPUImgPipeNext.argtypes = [c.c_void_p, c.POINTER(c.c_float),
                                     c.POINTER(c.c_float)]
    lib.MXTPUImgPipeDestroy.argtypes = [c.c_void_p]

    lib.MXTPUGetLastError.restype = c.c_char_p
    lib.MXTPUSetLastError.argtypes = [c.c_char_p]
    lib.MXTPURegisterOp.restype = c.c_int
    lib.MXTPURegisterOp.argtypes = [
        c.c_char_p, c.c_char_p, c.POINTER(c.c_char_p), c.c_int,
        c.POINTER(c.c_char_p), c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.c_int]
    lib.MXTPUListOps.restype = c.c_int
    lib.MXTPUListOps.argtypes = [c.POINTER(c.c_int),
                                 c.POINTER(c.POINTER(c.c_char_p))]
    lib.MXTPUGetOpInfo.restype = c.c_int
    lib.MXTPUGetOpInfo.argtypes = [
        c.c_char_p, c.POINTER(c.c_char_p), c.POINTER(c.c_int),
        c.POINTER(c.POINTER(c.c_char_p)), c.POINTER(c.c_int),
        c.POINTER(c.POINTER(c.c_char_p)), c.POINTER(c.POINTER(c.c_char_p)),
        c.POINTER(c.POINTER(c.c_char_p))]
    return lib


def find_lib_path():
    """Paths of the native library (reference libinfo.py find_lib_path
    contract: non-empty list or RuntimeError).  Triggers the lazy build
    the same way loading does, so a fresh checkout with a toolchain
    still returns a usable path."""
    find_lib()
    if not os.path.exists(_LIB_PATH):
        raise RuntimeError(
            f"Cannot find the native library: tried {_LIB_PATH} and "
            f"building from {_SRC_DIR} failed (set MXNET_TPU_NO_NATIVE "
            "to run pure-Python)")
    return [_LIB_PATH]


def find_lib():
    """Load (building if needed) the native library, or None."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("MXNET_TPU_NO_NATIVE"):
            return None
        # Always run make: it is an incremental no-op when the .so is
        # current, and rebuilds it when a src/*.cc is newer (the .so is
        # a local build product, never committed).
        if not _build() and not os.path.exists(_LIB_PATH):
            return None
        try:
            _LIB = _declare(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _LIB = None
        except AttributeError:
            # a stale locally-built .so missing newer symbols: try one
            # rebuild, else degrade to pure-Python like any other failure
            _LIB = None
            if not os.environ.get("MXNET_TPU_NO_NATIVE") and _build():
                try:
                    _LIB = _declare(ctypes.CDLL(_LIB_PATH))
                except (OSError, AttributeError):
                    _LIB = None
        return _LIB
