"""Device context.

Rebuild of the reference's ``python/mxnet/context.py`` (Context class,
``mx.cpu()/mx.gpu()``, with-statement device stack) for a JAX/TPU backend.

A ``Context`` names a logical device ``(device_type, device_id)`` and
resolves lazily to a concrete ``jax.Device``.  Mapping rules:

- ``tpu`` -> jax TPU devices (falls back to the default platform when no
  TPU is present, so code written for TPU runs under the CPU backend used
  in tests with ``--xla_force_host_platform_device_count=N``).
- ``gpu``  -> alias for ``tpu`` (migration aid: reference examples use
  ``mx.gpu(i)``; here they land on TPU chips).
- ``cpu`` / ``cpu_pinned`` -> jax CPU devices.

The reference's model-parallel tests rely on ``mx.cpu(0)`` and
``mx.cpu(1)`` being distinct schedulable devices
(tests/python/unittest/test_model_parallel.py) — that property holds here
whenever multiple XLA host devices are configured.
"""

from __future__ import annotations

import threading

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_devices"]


class Context:
    """A logical device (device_type, device_id), usable as a with-block."""

    _default_ctx = threading.local()

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 4}

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in self.devstr2type:
                raise ValueError(f"unknown device type {device_type!r}")
            self.device_typeid = self.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_type(self) -> str:
        return self.devtype2str[self.device_typeid]

    # -- jax resolution ----------------------------------------------------
    def jax_device(self):
        """Resolve to a concrete jax.Device (cached per process)."""
        import jax

        devs = _platform_devices(self.device_type)
        if self.device_id >= len(devs):
            raise ValueError(
                f"{self} out of range: only {len(devs)} {self.device_type} device(s) available"
            )
        return devs[self.device_id]

    # -- identity ----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return f"{self.device_type}({self.device_id})"

    def __str__(self):
        return self.__repr__()

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        Context._default_ctx.value = self._old_ctx
        return False


def _platform_devices(device_type: str):
    """Devices for a device_type, with graceful fallback (memoized)."""
    import jax

    key = device_type
    cache = _platform_devices._cache
    if key in cache:
        return cache[key]
    order = {
        "cpu": ["cpu"],
        "cpu_pinned": ["cpu"],
        "tpu": ["tpu", None],
        "gpu": ["tpu", "gpu", None],
    }[device_type]
    # local (process-addressable) devices only: context ids are
    # per-process, like the reference's per-worker device ordinals —
    # matters under jax.distributed where jax.devices() is global
    devs = None
    for plat in order:
        try:
            candidates = jax.devices(plat) if plat else jax.devices()
            local = [d for d in candidates
                     if d.process_index == jax.process_index()]
            devs = local or candidates
            break
        except RuntimeError:
            continue
    if devs is None:
        devs = jax.local_devices()
    cache[key] = devs
    return devs


_platform_devices._cache = {}


def cpu(device_id=0) -> Context:
    return Context("cpu", device_id)


def cpu_pinned(device_id=0) -> Context:
    return Context("cpu_pinned", device_id)


def gpu(device_id=0) -> Context:
    """Accelerator context (alias family: on this framework, a TPU chip)."""
    return Context("gpu", device_id)


def tpu(device_id=0) -> Context:
    return Context("tpu", device_id)


def num_devices(device_type="tpu") -> int:
    return len(_platform_devices(device_type))


def current_context() -> Context:
    """The ambient default context (reference context.py:108)."""
    cur = getattr(Context._default_ctx, "value", None)
    return cur if cur is not None else Context("cpu", 0)
